"""VMEM-resident one-hot MXU walk for small mesh partitions (Pallas).

The production walk kernels (``ops/walk.py``, ``parallel/partition.py
walk_local``) fetch each particle's current tet row from the packed
``[L,20]`` walk table with a random-row HBM gather — measured as the
hot loop's bandwidth floor (~80 B/crossing at row-granularity DMA
rates, docs/PERF_NOTES.md). When a PARTITION is small enough that its
table fits VMEM (~16 MB/core on v5e; a [4k,32] f32 table is 0.5 MB),
the gather can instead be a one-hot matmul executed entirely on-chip:

    row[W,32]  = onehot(lelem)[W,L] @ table[L,32]     (row fetch)
    flux[L]   += contrib[1,W] @ onehot(lelem)[W,L]    (tally scatter)

``vmem_walk_local`` is a drop-in for ``walk_local``'s walk itself (same
pause/ownership semantics: exit faces whose neighbor lives on another
chip set ``pending`` and park the particle for migration) as ONE Pallas
kernel per particle tile: the table is pinned in VMEM, the whole
while-loop runs inside the kernel (no per-iteration XLA op boundaries,
no HBM round-trips for the loop carries), and the tile's flux partial
accumulates on-chip and is written once.

Cost model (why only small L wins): the MXU work is 2·W·L·32 FLOPs per
iteration regardless of the active fraction — ~3-5x under the measured
gather floor at L≈512-1k, a wash by L≈4k (prototype analysis:
tools/exp_r3_vmem.py). The ``TallyConfig.walk_vmem_max_elems`` knob
gates it accordingly, on the PER-CHIP element count.

Numerical contract: NOT bitwise-identical to ``walk_local`` — the
per-face projections are computed column-wise (Mosaic-lowerable form)
instead of via the einsum, so results can differ in the last ulp; a
destination exactly ON a tet face may then commit the face-adjacent
neighbor element (the same benign divergence class partitioned mode
already documents vs the replicated walk). Track lengths, committed
positions, pause points and flux agree to rounding; the engines'
conservation gates apply unchanged.

No compaction cascade: lock-step waste costs MXU flops here, not
gathers, and the one-hot tile shape is fixed — the while_loop exits as
soon as the tile is all done/paused, which serves the same purpose at
tile granularity.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pumiumtally_tpu.mesh.tetmesh import (
    WALK_TABLE_ADJ,
    WALK_TABLE_NORMALS,
    WALK_TABLE_OFFSETS,
)

# Table rows are padded [L,20] -> [L,TABLE_PAD_COLS] so the MXU operand
# has a lane-aligned minor dimension. Column bases come from the shared
# packed-table layout constants so a reorder there cannot silently skew
# this kernel's reads.
_N0 = WALK_TABLE_NORMALS.start
_O0 = WALK_TABLE_OFFSETS.start
_A0 = WALK_TABLE_ADJ.start
TABLE_PAD_COLS = 32
# Mosaic block-shape law (jax pallas/mosaic/lowering.py
# _check_block_mappings): a rank-1 block must equal the whole array or
# be a multiple of 128*(32/bitwidth) lanes; a rank-2 block's minor dim
# must be a 128-multiple (or whole) and its second-minor an 8-multiple
# (or whole). Every ref this kernel touches is therefore f32/int32 —
# int8/bool would demand 512-wide rank-1 blocks.
#
# The lowering check is necessary, not sufficient: XLA lays out 1-D
# f32/s32 arrays in T(1024) tiles (one (8,128) vreg set), and Mosaic
# verifies the operand layout against the BLOCK size — a 256-wide
# rank-1 block on a 4096-long array fails with "XLA layout {0:T(1024)}
# does not match Mosaic layout {0:T(256)}" (first-contact log,
# tools/r4_onchip/). So every rank-1 tile — w_tile, the padded block
# row count Lp, and the iters output — is a TILE_1D multiple.
TILE_1D = 1024
W_TILE_DEFAULT = 1024
# Measured VMEM feasibility (chipless AOT sweeps,
# tools/aot_vmem_compile.py, v5e 16 MB/core). The r5 re-measurement
# corrected a round-4 conflation: the scoped-VMEM OOM is driven by the
# PARTICLE TILE, not the block length — at w_tile=2048 Mosaic's stack
# wants 20.8-21.9 MB regardless of Lp (1536 and 3993 both rejected,
# "scoped allocation ... exceeded scoped vmem limit"), while at the
# production default w_tile=1024 every swept block length through
# Lp=8232 compiles (r4 had recorded "Lp<=2048" from the w=2048 rows).
# Engines clamp the user's walk_vmem_max_elems to the value measured
# at W_TILE_DEFAULT on compiled-TPU backends (interpret mode has no
# ceiling); the perf sweet spot remains SMALL blocks regardless (the
# one-hot matmul costs ~2*L*128 FLOPs per crossing — module cost
# model), so the clamp is a compile-safety rail, not a tuning hint.
# The limit is a COMPILER constant, not physical VMEM — the same
# w=2048 kernel is rejected with the identical "limit 16.00M" on a
# v5p target with 2x the VMEM — so the ceiling applies to every chip
# generation; _chip_vmem_ceiling provides only an env override.
VMEM_FEASIBLE_MAX_ELEMS = 8192
# PROJECTED ceiling for a bf16 SELECT-tier resident table (the
# two-tier layout, docs/PERF_NOTES.md "Table precision tiers"): the
# [Lp,16] bf16 operand is 32 B/elem vs the f32 [Lp,32]-padded 128 B —
# at the binding w_tile=1024 the scoped stack is tile-driven (r5 law
# above), so halved TABLE bytes should extend the feasible block
# length ~2x. UNVERIFIED until the next chip window's AOT sweep
# (tools/r13_onchip_suite.sh) — THIS kernel does not lower the
# two-tier walk (bf16 lanes cannot hold adjacency ids, and a resident
# f32 refinement operand would give back the saving); the two-tier
# lowering lives in ops/pallas_walk.py (walk_kernel='pallas', round
# 17), which streams both tiers through the grid pipeline under this
# same 2x ceiling. With walk_kernel='vmem', engines route bf16
# blocked walks through the gather kernel and LOG the reroute
# (parallel/partition.py resolve_block_kernel) so the silent-fallback
# era is over — the constant still sizes that sub-split.
VMEM_FEASIBLE_MAX_ELEMS_BF16 = 2 * VMEM_FEASIBLE_MAX_ELEMS


def _chip_vmem_ceiling(table_dtype: str = "float32") -> int:
    """The block-size ceiling actually in force.

    PUMIUMTALLY_VMEM_CEILING_ELEMS overrides outright (a new chip
    generation or compiler flag change can be measured and pinned
    without a code change). Otherwise the measured default applies to
    EVERY chip generation: the r5 cross-topology AOT sweep
    (tools/aot_multichip_compile.py) showed the binding constraint is
    Mosaic's scoped-VMEM *stack* limit — a compiler-level constant
    (same "limit 16.00M" rejection on a v5p:1x1x1 target, whose
    physical VMEM is 2x v5e's) — so scaling the ceiling by physical
    per-core VMEM, as the first ADVICE-r4 fix did, was the wrong model.
    Operators raising the compiler's scoped limit
    (--xla_tpu_scoped_vmem_limit_kib) can raise this via the env.
    A bf16 select-tier table gets the PROJECTED doubled default (see
    VMEM_FEASIBLE_MAX_ELEMS_BF16) — the env override still wins."""
    import os

    env = os.environ.get("PUMIUMTALLY_VMEM_CEILING_ELEMS")
    if env:
        return int(env)
    if table_dtype == "bfloat16":
        return VMEM_FEASIBLE_MAX_ELEMS_BF16
    return VMEM_FEASIBLE_MAX_ELEMS


def effective_vmem_bound(
    bound: Optional[int], table_dtype: str = "float32"
) -> Optional[int]:
    """The walk_vmem_max_elems value an engine may actually use:
    clamped to the scoped-VMEM ceiling (measured default or env
    override — _chip_vmem_ceiling) on compiled-TPU backends (a larger
    bound would die in Mosaic's allocator at first compile), untouched
    in interpret mode. EVERY path that derives a partition from the
    knob must clamp through here — clamping after a partition is built
    leaves blocks the kernel cannot run (the sub-split constructor
    then rejects the configuration).

    ``table_dtype="bfloat16"`` applies the PROJECTED bf16 select-tier
    ceiling (VMEM_FEASIBLE_MAX_ELEMS_BF16). That path never reaches
    THIS kernel (engines reroute bf16 blocked walks to the gather
    kernel, with a logged diagnostic), but it is the binding sub-split
    bound for the pallas streaming kernel (ops/pallas_walk.py), whose
    per-block resident operands obey the same scoped-stack law."""
    if bound is None:
        return None
    bound = int(bound)
    if backend_needs_interpret():
        return bound
    ceiling = _chip_vmem_ceiling(table_dtype)
    if bound > ceiling:
        from pumiumtally_tpu.utils.logging import get_logger

        get_logger().warning(
            "walk_vmem_max_elems=%d exceeds the scoped-VMEM "
            "feasibility ceiling (%d) on this backend; clamping",
            bound, ceiling,
        )
        return ceiling
    return bound


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def pad_table(table: jnp.ndarray) -> jnp.ndarray:
    """[L,20] walk table -> [L,32] zero-padded MXU operand."""
    L, c = table.shape
    return jnp.concatenate(
        [table, jnp.zeros((L, TABLE_PAD_COLS - c), table.dtype)], axis=1
    )


def backend_needs_interpret() -> bool:
    """Mosaic lowering exists only on TPU backends; everywhere else
    (the CPU parity/test environments) the kernel runs in pallas
    interpret mode — same semantics, no compiled-kernel speed."""
    return jax.default_backend() not in ("tpu", "axon")


def _advance_cols(
    row, s, lelem, done, exited, pending, dest, d0, eff_w, tol, one, tally
):
    """One lock-step iteration from a fetched [W,32] row, column-wise
    (no [W,4,3] reshape/einsum — the Mosaic-lowerable form). Mirrors
    ``walk_local``'s advance semantics exactly: same crossing
    predicate, same first-minimal-face tie-break (argmin), same
    pause/boundary/reach transitions."""
    active = (~done) & (pending < 0)
    a_list, b_list = [], []
    for f in range(4):
        nx, ny, nz = (row[:, _N0 + 3 * f], row[:, _N0 + 3 * f + 1],
                      row[:, _N0 + 3 * f + 2])
        a_f = nx * d0[:, 0] + ny * d0[:, 1] + nz * d0[:, 2]
        n_dest = nx * dest[:, 0] + ny * dest[:, 1] + nz * dest[:, 2]
        # b = off - n·x0, with x0 = dest - d0 (the ray's start).
        b_f = row[:, _O0 + f] - n_dest + a_f
        a_list.append(a_f)
        b_list.append(b_f)
    inf = jnp.asarray(jnp.inf, s.dtype)
    s_fs = []
    for f in range(4):
        crossing = a_list[f] * (one - s) > tol
        s_f = jnp.where(
            crossing, b_list[f] / jnp.where(crossing, a_list[f], one), inf
        )
        s_fs.append(jnp.maximum(s_f, s))
    s_exit = jnp.minimum(
        jnp.minimum(s_fs[0], s_fs[1]), jnp.minimum(s_fs[2], s_fs[3])
    )
    adj = [row[:, _A0 + f].astype(jnp.int32) for f in range(4)]
    nxt = adj[3]
    for f in (2, 1, 0):  # first minimal face wins (matches argmin)
        nxt = jnp.where(s_fs[f] == s_exit, adj[f], nxt)
    reached = s_exit >= one
    s_new = jnp.where(reached, one, s_exit)
    hit_boundary = (~reached) & (nxt == -1)
    goes_remote = (~reached) & (nxt <= -2)

    contrib = (
        jnp.where(active, (s_new - s) * eff_w, 0.0) if tally else None
    )

    moving = active & ~reached & ~hit_boundary & ~goes_remote
    lelem = jnp.where(moving, nxt, lelem)
    s = jnp.where(active, s_new, s)
    pending = jnp.where(active & goes_remote, -nxt - 2, pending)
    done = done | (active & (reached | hit_boundary))
    exited = exited | (active & hit_boundary)
    return s, lelem, done, exited, pending, contrib


def vmem_walk_local(
    table: jnp.ndarray,  # [L,20] this chip's walk rows
    x: jnp.ndarray,  # [S,3]
    lelem: jnp.ndarray,  # [S] local element ids
    dest: jnp.ndarray,  # [S,3]
    flying: jnp.ndarray,  # [S] int8
    weight: jnp.ndarray,  # [S]
    done: jnp.ndarray,  # [S] bool
    exited: jnp.ndarray,  # [S] bool
    flux: jnp.ndarray,  # [L] owned flux
    *,
    tally: bool,
    tol: float,
    max_iters: int,
    w_tile: int = W_TILE_DEFAULT,
    interpret: Optional[bool] = None,
    vma: Optional[frozenset] = None,
    blocks: int = 1,
) -> Tuple[jnp.ndarray, ...]:
    """Drop-in for ``parallel.partition.walk_local`` (minus its cascade
    knobs): returns ``(x, lelem, done, exited, pending, flux, iters)``
    with identical pause/boundary semantics, computed by the VMEM
    one-hot kernel above. ``iters`` is the max over tiles.

    Requires local adjacency ids representable in the float table
    (``adj_int is None`` partitions — always true at VMEM-scale L).

    ``blocks``: sub-split mode. The table is ``blocks`` stacked
    [L,cols] block tables ([blocks*L, cols] rows), the slot arrays are
    grouped by block (``cap_b = S // blocks`` slots each, ``lelem``
    block-local, flux [blocks*L]), and the pallas grid becomes
    (blocks × tiles) — each grid step pins ONE block's [L,32] table in
    VMEM. Cross-block exits pause exactly like cross-chip exits (the
    partition's adjacency encodes every non-local neighbor as a
    remote glid); the caller migrates between rounds at block
    granularity. This is how a chip whose whole partition exceeds VMEM
    still runs the one-hot kernel: L is the BLOCK size, not the chip's
    element count. Requires ``S % blocks == 0`` and
    ``cap_b % w_tile == 0`` (the engine rounds its per-block capacity
    up to the tile size).

    ``vma``: the mesh axis names the outputs vary over when called
    inside ``shard_map`` with varying-axis checking on. Currently
    UNUSED by the engines: this jax version's pallas interpret path
    re-traces kernels with physical types that drop the tags, so the
    partitioned engine disables ``check_vma`` for its vmem round
    program instead (see partition.py) and passes nothing here. Kept
    (with the matching ``lax.pvary`` of the kernel's iota) for a jax
    where the interpret path is consistent.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = backend_needs_interpret()
    fdtype = x.dtype
    blocks = int(blocks)
    L = table.shape[0] // blocks
    n = x.shape[0]
    if n == 0:  # walk_local handles the empty batch; match it
        return (x, lelem, done, exited, jnp.full((0,), -1, jnp.int32),
                flux, jnp.asarray(0, jnp.int32))
    # Mosaic-legal tile width: rank-1 blocks must be TILE_1D multiples
    # (see layout law above). Rounding up (not clamping to n) keeps
    # every layout the hardware path accepts; interpret mode uses the
    # identical layout so CPU parity tests exercise exactly what
    # lowers.
    w_tile = _round_up(max(int(w_tile), 1), TILE_1D)
    if blocks > 1:
        # Sub-split layout is engine-arranged: no padding here, the
        # slot grouping IS the block routing.
        if n % blocks or (n // blocks) % w_tile:
            raise ValueError(
                f"blocked vmem walk needs slots divisible into "
                f"blocks x k x w_tile, got S={n}, blocks={blocks}, "
                f"w_tile={w_tile}"
            )
        pad = 0
    else:
        pad = (-n) % w_tile
        if pad:
            def padv(a, fill):
                return jnp.concatenate(
                    [a, jnp.full((pad, *a.shape[1:]), fill, a.dtype)]
                )

            x, dest = padv(x, 0.0), padv(dest, 0.0)
            lelem = padv(lelem, 0)
            flying = padv(flying, 0)
            weight = padv(weight, 0.0)
            done = padv(done, True)  # pad slots are inert
            exited = padv(exited, False)

    d0 = dest - x
    seg_len = jnp.linalg.norm(d0, axis=1)
    eff_w = jnp.where(flying.astype(bool), weight * seg_len, 0.0)
    T = (n + pad) // w_tile // blocks  # tiles per block
    max_iters = int(max_iters)
    # Pad each block's table to Lp rows (TILE_1D multiple): the
    # [Lp,32] input block and the rank-1 [Lp] flux output block are
    # then layout-legal for ANY mesh size, and Lp is the MXU-friendly
    # contraction dim. lelem < L always, so padded rows are never
    # selected by the one-hot and contribute nothing.
    Lp = _round_up(L, TILE_1D)
    table_p = pad_table(table)
    if Lp != L:
        cols = table_p.shape[1]
        table_p = jnp.concatenate(
            [table_p.reshape(blocks, L, cols),
             jnp.zeros((blocks, Lp - L, cols), table_p.dtype)], axis=1
        ).reshape(blocks * Lp, cols)

    def kernel(table_ref, x_ref, lelem_ref, dest_ref, effw_ref, done_ref,
               exited_ref, s_out, lelem_out, done_out, exited_out,
               pending_out, it_out, *flux_outs):
        flux_out = flux_outs[0] if tally else None
        table_v = table_ref[:]
        x0 = x_ref[:]
        dest_c = dest_ref[:]
        d0_c = dest_c - x0
        effw_c = effw_ref[:]
        one_k = jnp.asarray(1.0, x0.dtype)
        iota = lax.broadcasted_iota(jnp.int32, (w_tile, Lp), 1)
        if vma and hasattr(lax, "pvary"):
            # Under shard_map's varying-axis checking, primitive
            # outputs computed from no input (the iota) stay
            # "unvarying" and refuse to combine with the varying ref
            # data — promote explicitly. (No-op guard: a pre-vma jax
            # has neither the checker nor the primitive.)
            iota = lax.pvary(iota, tuple(vma))

        # flux and iters live in per-BLOCK output blocks revisited by
        # every tile t of the block (index_map ignores t): zero them on
        # the block's first tile, then reduce in VMEM across tiles —
        # the standard Pallas revisited-block reduction. This replaces
        # per-(block, tile) partials, whose (1, L) block shape the
        # Mosaic law forbids.
        t_id = pl.program_id(1)

        @pl.when(t_id == 0)
        def _init():
            it_out[:] = jnp.zeros_like(it_out)
            if tally:
                flux_out[:] = jnp.zeros_like(flux_out)

        # Loop state lives in the per-tile OUTPUT refs, mutated in
        # place each iteration; the while carry is two scalars. Mosaic
        # cannot legalize big functional while carries — the round-4
        # on-chip log (tools/r4_onchip/bench.log) shows `scf.yield`
        # failing with the flux vector unrolled into hundreds of vregs
        # — so ref mutation is not a style choice here, it is what
        # lowers. The active count rides the carry (computed by the
        # previous body pass) so `cond` stays a pure function of the
        # carry. Ref seeds are derived from kernel INPUTS, not literal
        # constants (x*0 instead of zeros_like): under shard_map a
        # literal is "unvarying" while the ref data varies over the
        # partition axis — same hazard walk_local documents; do not
        # "simplify" these.
        s_out[:] = x0[:, 0] * jnp.asarray(0, x0.dtype)
        lelem_out[:] = lelem_ref[:]
        done_out[:] = done_ref[:]
        exited_out[:] = exited_ref[:]
        pending_out[:] = (lelem_ref[:] - lelem_ref[:]) - 1

        def body(carry):
            it, _n_active = carry
            s = s_out[:]
            lelem = lelem_out[:]
            done = done_out[:] != 0
            exited = exited_out[:] != 0
            pending = pending_out[:]
            oh = (lelem[:, None] == iota).astype(table_v.dtype)
            row = jnp.dot(oh, table_v,
                          preferred_element_type=table_v.dtype)
            s, lelem, done, exited, pending, contrib = _advance_cols(
                row, s, lelem, done, exited, pending, dest_c, d0_c,
                effw_c, tol, one_k, tally,
            )
            s_out[:] = s
            lelem_out[:] = lelem
            done_out[:] = done.astype(jnp.int32)
            exited_out[:] = exited.astype(jnp.int32)
            pending_out[:] = pending
            if tally:
                # A no-tally walk (localization, phase A) accumulates
                # nothing provably zero.
                flux_out[:] = flux_out[:] + jnp.dot(
                    contrib[None, :], oh,
                    preferred_element_type=flux_out.dtype,
                )[0]
            n_active = jnp.sum(
                ((~done) & (pending < 0)).astype(jnp.int32)
            )
            return it + jnp.int32(1), n_active

        def cond(carry):
            it, n_active = carry
            return (it < max_iters) & (n_active > 0)

        n0 = jnp.sum((done_ref[:] == 0).astype(jnp.int32))
        it, _ = lax.while_loop(cond, body, (jnp.int32(0), n0))
        it_out[:] = jnp.maximum(it_out[:], it)

    # Uniform (blocks, tiles-per-block) grid: blocks=1 degenerates to
    # the flat tiling. Each grid step (b, t) pins block b's [Lp,32]
    # table in VMEM and walks tile t of that block's slot group.
    S = T * w_tile * blocks
    tile = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile,), lambda b, t: (b * T + t,))
    tile3 = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile, 3), lambda b, t: (b * T + t, 0))
    out_specs = [
        tile(), tile(), tile(), tile(), tile(),
        pl.BlockSpec((TILE_1D,), lambda b, t: (b,)),
    ]
    # vma is a vma-era concept: only spell the kwarg when the caller
    # actually passed axes (ShapeDtypeStruct on jax 0.4.x predates it).
    def sds(shape, dtype):
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        return jax.ShapeDtypeStruct(shape, dtype)

    out_shape = [
        sds((S,), fdtype),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((S,), jnp.int32),
        sds((blocks * TILE_1D,), jnp.int32),
    ]
    if tally:
        out_specs.append(pl.BlockSpec((Lp,), lambda b, t: (b,)))
        out_shape.append(sds((blocks * Lp,), flux.dtype))
    s_o, lelem_o, done_o, exited_o, pending_o, iters, *fparts = (
        pl.pallas_call(
            kernel,
            grid=(blocks, T),
            in_specs=[
                pl.BlockSpec((Lp, TABLE_PAD_COLS), lambda b, t: (b, 0)),
                tile3(), tile(), tile3(), tile(), tile(), tile(),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(table_p, x, lelem, dest, eff_w,
          done.astype(jnp.int32), exited.astype(jnp.int32))
    )

    s_o, lelem_o = s_o[:n], lelem_o[:n]
    done_o = done_o[:n] != 0
    exited_o = exited_o[:n] != 0
    pending_o = pending_o[:n]
    dest, d0 = dest[:n], d0[:n]
    x0 = dest - d0
    if tally:
        # Per-block accumulated partials [blocks, Lp]: drop the row
        # padding, flatten back to the [blocks*L] flux layout.
        flux = flux + fparts[0].reshape(blocks, Lp)[:, :L].reshape(
            blocks * L
        )
    # Same materialization rule as walk_local: reached-dest commits
    # dest bit-exactly; everyone else (boundary leavers AND paused
    # particles) commits x0 + s·d0.
    x_fin = jnp.where(
        (done_o & ~exited_o)[:, None], dest, x0 + s_o[:, None] * d0
    )
    return x_fin, lelem_o, done_o, exited_o, pending_o, flux, jnp.max(iters)
