"""VMEM-resident one-hot MXU walk for small mesh partitions (Pallas).

The production walk kernels (``ops/walk.py``, ``parallel/partition.py
walk_local``) fetch each particle's current tet row from the packed
``[L,20]`` walk table with a random-row HBM gather — measured as the
hot loop's bandwidth floor (~80 B/crossing at row-granularity DMA
rates, docs/PERF_NOTES.md). When a PARTITION is small enough that its
table fits VMEM (~16 MB/core on v5e; a [4k,32] f32 table is 0.5 MB),
the gather can instead be a one-hot matmul executed entirely on-chip:

    row[W,32]  = onehot(lelem)[W,L] @ table[L,32]     (row fetch)
    flux[L]   += contrib[1,W] @ onehot(lelem)[W,L]    (tally scatter)

``vmem_walk_local`` is a drop-in for ``walk_local``'s walk itself (same
pause/ownership semantics: exit faces whose neighbor lives on another
chip set ``pending`` and park the particle for migration) as ONE Pallas
kernel per particle tile: the table is pinned in VMEM, the whole
while-loop runs inside the kernel (no per-iteration XLA op boundaries,
no HBM round-trips for the loop carries), and the tile's flux partial
accumulates on-chip and is written once.

Cost model (why only small L wins): the MXU work is 2·W·L·32 FLOPs per
iteration regardless of the active fraction — ~3-5x under the measured
gather floor at L≈512-1k, a wash by L≈4k (prototype analysis:
tools/exp_r3_vmem.py). The ``TallyConfig.walk_vmem_max_elems`` knob
gates it accordingly, on the PER-CHIP element count.

Numerical contract: NOT bitwise-identical to ``walk_local`` — the
per-face projections are computed column-wise (Mosaic-lowerable form)
instead of via the einsum, so results can differ in the last ulp; a
destination exactly ON a tet face may then commit the face-adjacent
neighbor element (the same benign divergence class partitioned mode
already documents vs the replicated walk). Track lengths, committed
positions, pause points and flux agree to rounding; the engines'
conservation gates apply unchanged.

No compaction cascade: lock-step waste costs MXU flops here, not
gathers, and the one-hot tile shape is fixed — the while_loop exits as
soon as the tile is all done/paused, which serves the same purpose at
tile granularity.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pumiumtally_tpu.mesh.tetmesh import (
    WALK_TABLE_ADJ,
    WALK_TABLE_NORMALS,
    WALK_TABLE_OFFSETS,
)

# Table rows are padded [L,20] -> [L,TABLE_PAD_COLS] so the MXU operand
# has a lane-aligned minor dimension. Column bases come from the shared
# packed-table layout constants so a reorder there cannot silently skew
# this kernel's reads.
_N0 = WALK_TABLE_NORMALS.start
_O0 = WALK_TABLE_OFFSETS.start
_A0 = WALK_TABLE_ADJ.start
TABLE_PAD_COLS = 32
W_TILE_DEFAULT = 256


def pad_table(table: jnp.ndarray) -> jnp.ndarray:
    """[L,20] walk table -> [L,32] zero-padded MXU operand."""
    L, c = table.shape
    return jnp.concatenate(
        [table, jnp.zeros((L, TABLE_PAD_COLS - c), table.dtype)], axis=1
    )


def backend_needs_interpret() -> bool:
    """Mosaic lowering exists only on TPU backends; everywhere else
    (the CPU parity/test environments) the kernel runs in pallas
    interpret mode — same semantics, no compiled-kernel speed."""
    return jax.default_backend() not in ("tpu", "axon")


def _advance_cols(
    row, s, lelem, done, exited, pending, dest, d0, eff_w, tol, one, tally
):
    """One lock-step iteration from a fetched [W,32] row, column-wise
    (no [W,4,3] reshape/einsum — the Mosaic-lowerable form). Mirrors
    ``walk_local``'s advance semantics exactly: same crossing
    predicate, same first-minimal-face tie-break (argmin), same
    pause/boundary/reach transitions."""
    active = (~done) & (pending < 0)
    a_list, b_list = [], []
    for f in range(4):
        nx, ny, nz = (row[:, _N0 + 3 * f], row[:, _N0 + 3 * f + 1],
                      row[:, _N0 + 3 * f + 2])
        a_f = nx * d0[:, 0] + ny * d0[:, 1] + nz * d0[:, 2]
        n_dest = nx * dest[:, 0] + ny * dest[:, 1] + nz * dest[:, 2]
        # b = off - n·x0, with x0 = dest - d0 (the ray's start).
        b_f = row[:, _O0 + f] - n_dest + a_f
        a_list.append(a_f)
        b_list.append(b_f)
    inf = jnp.asarray(jnp.inf, s.dtype)
    s_fs = []
    for f in range(4):
        crossing = a_list[f] * (one - s) > tol
        s_f = jnp.where(
            crossing, b_list[f] / jnp.where(crossing, a_list[f], one), inf
        )
        s_fs.append(jnp.maximum(s_f, s))
    s_exit = jnp.minimum(
        jnp.minimum(s_fs[0], s_fs[1]), jnp.minimum(s_fs[2], s_fs[3])
    )
    adj = [row[:, _A0 + f].astype(jnp.int32) for f in range(4)]
    nxt = adj[3]
    for f in (2, 1, 0):  # first minimal face wins (matches argmin)
        nxt = jnp.where(s_fs[f] == s_exit, adj[f], nxt)
    reached = s_exit >= one
    s_new = jnp.where(reached, one, s_exit)
    hit_boundary = (~reached) & (nxt == -1)
    goes_remote = (~reached) & (nxt <= -2)

    contrib = (
        jnp.where(active, (s_new - s) * eff_w, 0.0) if tally else None
    )

    moving = active & ~reached & ~hit_boundary & ~goes_remote
    lelem = jnp.where(moving, nxt, lelem)
    s = jnp.where(active, s_new, s)
    pending = jnp.where(active & goes_remote, -nxt - 2, pending)
    done = done | (active & (reached | hit_boundary))
    exited = exited | (active & hit_boundary)
    return s, lelem, done, exited, pending, contrib


def vmem_walk_local(
    table: jnp.ndarray,  # [L,20] this chip's walk rows
    x: jnp.ndarray,  # [S,3]
    lelem: jnp.ndarray,  # [S] local element ids
    dest: jnp.ndarray,  # [S,3]
    flying: jnp.ndarray,  # [S] int8
    weight: jnp.ndarray,  # [S]
    done: jnp.ndarray,  # [S] bool
    exited: jnp.ndarray,  # [S] bool
    flux: jnp.ndarray,  # [L] owned flux
    *,
    tally: bool,
    tol: float,
    max_iters: int,
    w_tile: int = W_TILE_DEFAULT,
    interpret: Optional[bool] = None,
    vma: Optional[frozenset] = None,
    blocks: int = 1,
) -> Tuple[jnp.ndarray, ...]:
    """Drop-in for ``parallel.partition.walk_local`` (minus its cascade
    knobs): returns ``(x, lelem, done, exited, pending, flux, iters)``
    with identical pause/boundary semantics, computed by the VMEM
    one-hot kernel above. ``iters`` is the max over tiles.

    Requires local adjacency ids representable in the float table
    (``adj_int is None`` partitions — always true at VMEM-scale L).

    ``blocks``: sub-split mode. The table is ``blocks`` stacked
    [L,cols] block tables ([blocks*L, cols] rows), the slot arrays are
    grouped by block (``cap_b = S // blocks`` slots each, ``lelem``
    block-local, flux [blocks*L]), and the pallas grid becomes
    (blocks × tiles) — each grid step pins ONE block's [L,32] table in
    VMEM. Cross-block exits pause exactly like cross-chip exits (the
    partition's adjacency encodes every non-local neighbor as a
    remote glid); the caller migrates between rounds at block
    granularity. This is how a chip whose whole partition exceeds VMEM
    still runs the one-hot kernel: L is the BLOCK size, not the chip's
    element count. Requires ``S % blocks == 0`` and
    ``cap_b % w_tile == 0`` (the engine rounds its per-block capacity
    up to the tile size).

    ``vma``: the mesh axis names the outputs vary over when called
    inside ``shard_map`` with varying-axis checking on. Currently
    UNUSED by the engines: this jax version's pallas interpret path
    re-traces kernels with physical types that drop the tags, so the
    partitioned engine disables ``check_vma`` for its vmem round
    program instead (see partition.py) and passes nothing here. Kept
    (with the matching ``lax.pvary`` of the kernel's iota) for a jax
    where the interpret path is consistent.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = backend_needs_interpret()
    fdtype = x.dtype
    blocks = int(blocks)
    L = table.shape[0] // blocks
    n = x.shape[0]
    if n == 0:  # walk_local handles the empty batch; match it
        return (x, lelem, done, exited, jnp.full((0,), -1, jnp.int32),
                flux, jnp.asarray(0, jnp.int32))
    if blocks > 1:
        # Sub-split layout is engine-arranged: no padding here, the
        # slot grouping IS the block routing.
        if n % blocks or (n // blocks) % w_tile:
            raise ValueError(
                f"blocked vmem walk needs slots divisible into "
                f"blocks x k x w_tile, got S={n}, blocks={blocks}, "
                f"w_tile={w_tile}"
            )
        pad = 0
    else:
        w_tile = min(int(w_tile), max(n, 1))
        pad = (-n) % w_tile
        if pad:
            def padv(a, fill):
                return jnp.concatenate(
                    [a, jnp.full((pad, *a.shape[1:]), fill, a.dtype)]
                )

            x, dest = padv(x, 0.0), padv(dest, 0.0)
            lelem = padv(lelem, 0)
            flying = padv(flying, 0)
            weight = padv(weight, 0.0)
            done = padv(done, True)  # pad slots are inert
            exited = padv(exited, False)

    d0 = dest - x
    seg_len = jnp.linalg.norm(d0, axis=1)
    eff_w = jnp.where(flying.astype(bool), weight * seg_len, 0.0)
    T = (n + pad) // w_tile // blocks  # tiles per block
    max_iters = int(max_iters)
    table_p = pad_table(table)

    def kernel(table_ref, x_ref, lelem_ref, dest_ref, effw_ref, done_ref,
               exited_ref, s_out, lelem_out, done_out, exited_out,
               pending_out, it_out, *flux_outs):
        flux_out = flux_outs[0] if tally else None
        table_v = table_ref[:]
        x0 = x_ref[:]
        dest_c = dest_ref[:]
        d0_c = dest_c - x0
        effw_c = effw_ref[:]
        one_k = jnp.asarray(1.0, x0.dtype)
        iota = lax.broadcasted_iota(jnp.int32, (w_tile, L), 1)
        if vma:
            # Under shard_map's varying-axis checking, primitive
            # outputs computed from no input (the iota) stay
            # "unvarying" and refuse to combine with the varying ref
            # data — promote explicitly.
            iota = lax.pvary(iota, tuple(vma))

        def body(carry):
            # The flux partial rides the carry only when tallying — a
            # no-tally walk (localization, phase A) then carries,
            # writes and reduces nothing provably zero.
            it, s, lelem, done, exited, pending, *fl = carry
            oh = (lelem[:, None] == iota).astype(table_v.dtype)
            row = jnp.dot(oh, table_v,
                          preferred_element_type=table_v.dtype)
            s, lelem, done, exited, pending, contrib = _advance_cols(
                row, s, lelem, done, exited, pending, dest_c, d0_c,
                effw_c, tol, one_k, tally,
            )
            if tally:
                fl = [fl[0] + jnp.dot(contrib[None, :], oh,
                                      preferred_element_type=fl[0].dtype)]
            return (it + jnp.int32(1), s, lelem, done, exited, pending,
                    *fl)

        def cond(carry):
            it, _s, _le, done, _ex, pending = carry[:6]
            return (it < max_iters) & jnp.any((~done) & (pending < 0))

        # Initial carries derived from kernel INPUTS, not literal
        # constants: under shard_map a literal is "unvarying" while the
        # loop outputs vary over the partition axis, which breaks the
        # while_loop carry typing (same hazard walk_local documents).
        lelem0 = lelem_ref[:]
        s0_k = x0[:, 0] * jnp.asarray(0, x0.dtype)
        pending0 = (lelem0 - lelem0) - 1
        init = (jnp.int32(0), s0_k, lelem0,
                done_ref[:] != 0, exited_ref[:] != 0, pending0)
        if tally:
            fl0 = (table_v[:, 0] * jnp.asarray(0, table_v.dtype)).astype(
                flux.dtype
            )[None, :]
            init = init + (fl0,)
        out = lax.while_loop(cond, body, init)
        it, s, lelem, done, exited, pending = out[:6]
        s_out[:] = s
        lelem_out[:] = lelem
        done_out[:] = done.astype(jnp.int8)
        exited_out[:] = exited.astype(jnp.int8)
        pending_out[:] = pending
        it_out[0] = it
        if tally:
            flux_out[:] = out[6]

    # Uniform (blocks, tiles-per-block) grid: blocks=1 degenerates to
    # the flat tiling. Each grid step (b, t) pins block b's [L,32]
    # table in VMEM and walks tile t of that block's slot group.
    S = T * w_tile * blocks
    tile = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile,), lambda b, t: (b * T + t,))
    tile3 = lambda: pl.BlockSpec(  # noqa: E731
        (w_tile, 3), lambda b, t: (b * T + t, 0))
    out_specs = [
        tile(), tile(), tile(), tile(), tile(),
        pl.BlockSpec((1,), lambda b, t: (b * T + t,)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((S,), fdtype, vma=vma),
        jax.ShapeDtypeStruct((S,), jnp.int32, vma=vma),
        jax.ShapeDtypeStruct((S,), jnp.int8, vma=vma),
        jax.ShapeDtypeStruct((S,), jnp.int8, vma=vma),
        jax.ShapeDtypeStruct((S,), jnp.int32, vma=vma),
        jax.ShapeDtypeStruct((T * blocks,), jnp.int32, vma=vma),
    ]
    if tally:
        out_specs.append(pl.BlockSpec((1, L), lambda b, t: (b * T + t, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((T * blocks, L), flux.dtype, vma=vma)
        )
    s_o, lelem_o, done_o, exited_o, pending_o, iters, *fparts = (
        pl.pallas_call(
            kernel,
            grid=(blocks, T),
            in_specs=[
                pl.BlockSpec((L, TABLE_PAD_COLS), lambda b, t: (b, 0)),
                tile3(), tile(), tile3(), tile(), tile(), tile(),
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(table_p, x, lelem, dest, eff_w,
          done.astype(jnp.int8), exited.astype(jnp.int8))
    )

    s_o, lelem_o = s_o[:n], lelem_o[:n]
    done_o = done_o[:n] != 0
    exited_o = exited_o[:n] != 0
    pending_o = pending_o[:n]
    dest, d0 = dest[:n], d0[:n]
    x0 = dest - d0
    if tally:
        # Per-(block, tile) partials reduce within the block, then lay
        # out as the [blocks*L] padded flux.
        flux = flux + fparts[0].reshape(blocks, T, L).sum(axis=1).reshape(
            blocks * L
        )
    # Same materialization rule as walk_local: reached-dest commits
    # dest bit-exactly; everyone else (boundary leavers AND paused
    # particles) commits x0 + s·d0.
    x_fin = jnp.where(
        (done_o & ~exited_o)[:, None], dest, x0 + s_o[:, None] * d0
    )
    return x_fin, lelem_o, done_o, exited_o, pending_o, flux, jnp.max(iters)
