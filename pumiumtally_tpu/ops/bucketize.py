"""Sort-free bucket partition: counting ranks over small-alphabet keys.

Every redistribution point in the engine — the compaction cascade's
stage boundaries (ops/walk.py), ``walk_local``'s in-round compaction
and slot-order restore (parallel/partition.py), and particle migration
(``_migrate_impl``) — needs the same primitive: given int keys drawn
from a SMALL alphabet over N slots, move every slot to its stable
within-bucket position (bucket 0 first, then bucket 1, …; original
slot order preserved inside each bucket). The seed implementation
bought this from a full-capacity stable ``argsort`` — measured 4.0 ms
per 500k keys on v5e (docs/PERF_NOTES.md r2 profile) — even though the
keys are done/paused flags (k = 2–3) or chip/block owners
(k = nparts + 1), for which counting ranks suffice:

    rank[i]  = #{j < i : key[j] == key[i]}       (per-bucket cumsum)
    start[b] = #{j : key[j] < b}                 (exclusive count scan)
    dest[i]  = start[key[i]] + rank[i]

``dest`` is a permutation of ``iota(N)``; scattering rows to it (or
gathering through the inverse permutation ``perm``) reproduces the
stable sort EXACTLY — same integer permutation, hence bitwise-identical
downstream results, pinned by tests/test_partition_rank.py. The rank
cumsum is a [k,N] one-hot scan, evaluated in bucket slabs of
``_RANK_SLAB`` so memory stays bounded when k is the block count of a
finely sub-split mesh (hundreds of blocks on a ~1M-tet lattice).

``method="argsort"`` computes the identical outputs through the old
stable-argsort machinery — kept as the parity reference and the A/B
arm (tools/exp_partition_ab.py), selectable end-to-end via
``TallyConfig.walk_partition_method``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# Bucket-slab width for the rank cumsum: bounds the one-hot
# intermediate at [_RANK_SLAB, N] however large the alphabet is
# (migration keys scale with the block count). 64 keeps the slab f32
# lane-aligned and the intermediate under ~0.3 MB per 1k slots.
_RANK_SLAB = 64

PARTITION_METHODS = ("rank", "argsort")


def _check_method(method: str) -> None:
    if method not in PARTITION_METHODS:
        raise ValueError(
            f"partition method must be one of {PARTITION_METHODS}, "
            f"got {method!r}"
        )


def _iota_like(key: jnp.ndarray) -> jnp.ndarray:
    # Derived from the input (not jnp.arange) so it carries the same
    # varying/replication type as the data under shard_map — the same
    # idiom as the cascade's slot-index carry (ops/walk.py).
    return jnp.cumsum(jnp.ones_like(key)) - 1


def bucket_counts(key: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """[num_buckets] occupancy of each bucket (a scatter-add, no sort)."""
    return jnp.bincount(key, length=int(num_buckets))


def counting_ranks(
    key: jnp.ndarray, num_buckets: int, *, method: str = "rank"
) -> jnp.ndarray:
    """Stable within-bucket rank of every slot, as int32.

    ``rank[i]`` counts the earlier slots sharing ``key[i]``'s bucket —
    exactly the rank a stable sort would assign inside the bucket.
    Keys must lie in ``[0, num_buckets)``.
    """
    _check_method(method)
    key = key.astype(jnp.int32)
    num_buckets = int(num_buckets)
    if method == "argsort":
        # Reference arm: the seed's post-sort rank machinery
        # (pos − starts[key]) un-permuted back to slot order.
        perm = jnp.argsort(key, stable=True)
        counts = bucket_counts(key, num_buckets)
        starts = jnp.concatenate(
            [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        pos = _iota_like(key)
        rank_sorted = pos - starts[key[perm]]
        return (
            jnp.zeros_like(key).at[perm].set(rank_sorted.astype(jnp.int32))
        )

    if num_buckets <= 2:
        # The cascade's hot case (done partition): one [N] cumsum.
        # ones_before = #{j <= i : key[j] == 1}; zeros get their slot
        # index minus the ones that preceded them.
        ones_before = jnp.cumsum(key)
        return jnp.where(
            key == 1, ones_before - 1, _iota_like(key) - ones_before
        ).astype(jnp.int32)

    slab = min(_RANK_SLAB, num_buckets)

    def slab_ranks(base):
        # One-hot membership of this slab's buckets: [slab, N] → an
        # inclusive cumsum along N is each slot's 1-based rank within
        # its bucket, valid where the slot's key falls in the slab.
        ids = base + lax.iota(jnp.int32, slab)
        onehot = (key[None, :] == ids[:, None]).astype(jnp.int32)
        csum = jnp.cumsum(onehot, axis=1)
        col = jnp.clip(key - base, 0, slab - 1)
        r = jnp.take_along_axis(csum, col[None, :], axis=0)[0] - 1
        in_slab = (key >= base) & (key < base + slab)
        return jnp.where(in_slab, r, 0)

    nslabs = -(-num_buckets // slab)
    if nslabs == 1:
        return slab_ranks(jnp.asarray(0, jnp.int32))
    # Large alphabets (finely sub-split meshes): accumulate slab by
    # slab so the one-hot intermediate never exceeds [_RANK_SLAB, N].
    return lax.fori_loop(
        0,
        nslabs,
        lambda s, acc: acc + slab_ranks(s * slab),
        jnp.zeros_like(key),
    )


def bucket_destinations(
    key: jnp.ndarray, num_buckets: int, *, method: str = "rank"
):
    """(dest, counts, starts): each slot's stable partitioned position.

    ``dest`` is the permutation a stable sort by ``key`` would apply:
    row i of the partitioned layout is original slot j with
    ``dest[j] == i``. Scatter rows to ``dest`` (``out.at[dest].set(rows)``)
    for the partitioned order in ONE row operation — no argsort, no
    permutation gather.
    """
    _check_method(method)
    key = key.astype(jnp.int32)
    counts = bucket_counts(key, int(num_buckets))
    starts = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    if method == "argsort":
        # Seed-faithful A/B arm: ONE stable argsort, dest = its
        # inverse. Charging this arm the rank-reconstruction machinery
        # instead would overstate the argsort path's cost and flatter
        # the rank arm in every recorded speedup.
        perm = jnp.argsort(key, stable=True)
        dest = (
            jnp.zeros_like(key).at[perm].set(_iota_like(key))
        ).astype(jnp.int32)
        return dest, counts, starts
    rank = counting_ranks(key, num_buckets, method=method)
    dest = starts[key].astype(jnp.int32) + rank
    return dest, counts, starts


def partition_perm(
    key: jnp.ndarray, num_buckets: int, *, method: str = "rank"
):
    """(perm, counts, starts) with ``perm == argsort(key, stable=True)``
    — bit-for-bit — computed from counting ranks via one small int
    scatter. For consumers that prefer gathering rows through the
    permutation (the cascade's packed stage boundary) over scattering
    them to ``dest``. ``method="argsort"`` IS the seed's direct stable
    argsort (no rank machinery), so end-to-end A/Bs charge that arm
    its true cost."""
    _check_method(method)
    if method == "argsort":
        key = key.astype(jnp.int32)
        counts = bucket_counts(key, int(num_buckets))
        starts = jnp.concatenate(
            [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        return jnp.argsort(key, stable=True), counts, starts
    dest, counts, starts = bucket_destinations(
        key, num_buckets, method=method
    )
    perm = jnp.zeros_like(dest).at[dest].set(_iota_like(dest))
    return perm, counts, starts


def unpermute(values: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Restore accumulated-permutation order: row i holds original slot
    ``idx[i]``; scatter rows home directly. Replaces the seed's
    ``values[argsort(idx)]`` (an argsort plus a gather) with one
    scatter — bitwise-identical, since both apply the same inverse
    permutation."""
    return jnp.zeros_like(values).at[idx].set(values)
