from pumiumtally_tpu.ops.walk import WalkResult, walk
from pumiumtally_tpu.ops import geometry

__all__ = ["WalkResult", "walk", "geometry"]
