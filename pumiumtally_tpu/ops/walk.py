"""The mesh-walk kernel: masked lock-step ray/tet traversal with tallying.

This is the TPU-native equivalent of the PUMIPic ``ParticleTracer::search``
adjacency walk plus the ``ParticleAtElemBoundary`` handler (SURVEY.md §2.2,
reference PumiTallyImpl.cpp:297-380 and the make_search_class fork): all
particles advance one element per iteration of a ``lax.while_loop`` until
every particle has either reached its destination or left the domain —
the same lock-step property as the reference's search loop (SURVEY.md
§3.3), but expressed as dense, static-shaped array ops XLA can fuse.

Per iteration, for every not-done particle:
  1. gather the 4 face planes + neighbor ids of its current tet
     (replaces PUMIPic's per-particle adjacency chase),
  2. exit parameter ``t_f = (off_f − n_f·x) / (n_f·d)`` over faces with
     ``n_f·d > tol`` — the ray/tet-face intersection (reference fork's
     search internals; semantics pinned by the oracles in BASELINE.md),
  3. tally ``‖Δx‖ · weight`` into the current element — the reference's
     ``EvaluateFlux`` + ``Kokkos::atomic_add`` (PumiTallyImpl.cpp:352-380)
     becomes a deterministic XLA scatter-add,
  4. vacuum BC: a particle whose exit face has no neighbor is done and
     its position clamps to the boundary intersection point — reference
     ``ApplyVacuumBC`` (PumiTallyImpl.cpp:256-286),
  5. advance to the neighbor tet — reference ``UpdateCurrentElement``
     (PumiTallyImpl.cpp:243-254).

Tally on/off is a static flag: the initial localization pass never
tallies (reference ``is_initial_track``, PumiTallyImpl.cpp:309) and the
relocate-to-origin phase runs with weights zeroed (PumiTallyImpl.cpp:105);
here both simply compile a no-tally variant of the loop body.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from pumiumtally_tpu.mesh.tetmesh import TetMesh


class WalkResult(NamedTuple):
    """Post-walk particle state.

    ``x`` is the committed position: the destination, clamped to the
    boundary intersection for particles that left the domain (the
    reference commits dest→origin after each search; clamp semantics at
    PumiTallyImpl.cpp:275-281, oracle test:242-245).
    ``elem`` is the final element (boundary leavers keep the last tet
    they were in, reference UpdateCurrentElement skips next==-1).
    """

    x: jnp.ndarray  # [N,3]
    elem: jnp.ndarray  # [N] int32
    done: jnp.ndarray  # [N] bool (False = walk iteration cap hit)
    exited: jnp.ndarray  # [N] bool: finished by leaving the domain (vacuum BC)
    flux: jnp.ndarray  # [E] accumulated track-length tally
    iters: jnp.ndarray  # [] int32: iterations taken


def walk(
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dest: jnp.ndarray,
    in_flight: jnp.ndarray,
    weight: jnp.ndarray,
    flux: jnp.ndarray,
    *,
    tally: bool,
    tol: float,
    max_iters: int,
) -> WalkResult:
    """Walk every particle from ``x`` (inside ``elem``) toward ``dest``.

    Particles with ``in_flight == 0`` must be given ``dest == x`` by the
    caller (hold position — reference PumiTallyImpl.cpp:100-103); they
    finish on the first iteration with zero tally contribution
    (EvaluateFlux skips them, PumiTallyImpl.cpp:364).
    """
    fdtype = x.dtype
    one = jnp.asarray(1.0, fdtype)
    # All-False initial done/exited masks, derived from an input so they
    # carry the same sharding/varying-axis type as the particle arrays
    # when this runs inside shard_map (a literal zeros() constant would
    # be "unvarying" and break the while_loop carry typing).
    active0 = in_flight != in_flight
    flying = in_flight.astype(bool)

    def cond(state):
        it, _x, _elem, done, _exited, _flux = state
        return (it < max_iters) & jnp.any(~done)

    def body(state):
        it, x, elem, done, exited, flux = state
        active = ~done
        d = dest - x  # remaining segment
        fn = mesh.face_normals[elem]  # [N,4,3]
        fo = mesh.face_offsets[elem]  # [N,4]
        adj = mesh.face_adj[elem]  # [N,4]
        denom = jnp.einsum("nfc,nc->nf", fn, d)
        numer = fo - jnp.einsum("nfc,nc->nf", fn, x)
        crossing = denom > tol
        t = jnp.where(crossing, numer / jnp.where(crossing, denom, one), jnp.inf)
        # x may sit epsilon-outside a face after a previous step; don't
        # step backwards.
        t = jnp.maximum(t, 0.0)
        t_exit = jnp.min(t, axis=1)
        f_exit = jnp.argmin(t, axis=1)
        # Destination inside the current tet (or no forward crossing at
        # all, e.g. zero-length segment) → done at dest.
        reached = t_exit >= one
        t_step = jnp.where(reached, one, t_exit)
        x_new = x + t_step[:, None] * d
        next_elem = jnp.take_along_axis(adj, f_exit[:, None], axis=1)[:, 0]
        hit_boundary = (~reached) & (next_elem == -1)

        if tally:
            seg = t_step * jnp.linalg.norm(d, axis=1)
            contrib = jnp.where(active & flying, seg * weight, 0.0)
            flux = flux.at[elem].add(contrib, mode="drop")

        advance = active & ~reached & ~hit_boundary
        elem = jnp.where(advance, next_elem, elem)
        x = jnp.where(active[:, None], x_new, x)
        done = done | reached | hit_boundary
        exited = exited | (active & hit_boundary)
        return it + 1, x, elem, done, exited, flux

    it0 = jnp.asarray(0, jnp.int32)
    it, x, elem, done, exited, flux = lax.while_loop(
        cond, body, (it0, x, elem, active0, active0, flux)
    )
    return WalkResult(x=x, elem=elem, done=done, exited=exited, flux=flux, iters=it)
