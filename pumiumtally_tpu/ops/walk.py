"""The mesh-walk kernel: masked lock-step ray/tet traversal with tallying.

This is the TPU-native equivalent of the PUMIPic ``ParticleTracer::search``
adjacency walk plus the ``ParticleAtElemBoundary`` handler (SURVEY.md §2.2,
reference PumiTallyImpl.cpp:297-380 and the make_search_class fork): all
particles advance one element per iteration of a ``lax.while_loop`` until
every particle has either reached its destination or left the domain —
the same lock-step property as the reference's search loop (SURVEY.md
§3.3), but expressed as dense, static-shaped array ops XLA can fuse.

The walk is parametrized by the scalar ray coordinate ``s ∈ [0,1]``
along the FIXED segment ``x0 → dest`` (``d0 = dest − x0``): for any tet
face, the intersection satisfies ``s_f = (off_f − n_f·x0) / (n_f·d0)``
— both projections are against walk-constant vectors, so no position
needs updating inside the loop (the classic per-step form
``t = (off − n·x)/(n·d)`` recomputes ``n·x`` against a moving point
every iteration); positions are materialized ONCE from ``s`` at the
end. Per iteration, for every not-done particle:
  1. gather the packed walk row of its current tet — 4 face planes +
     4 neighbor ids in ONE contiguous [20]-float row (replaces PUMIPic's
     per-particle adjacency chase; packing measured ~2.6× faster than
     three separate gathers on TPU). Under ``table_dtype="bfloat16"``
     this splits into the two-tier form: a half-width bf16 SELECT row
     picks the exit face and ONE full-precision refinement row of the
     winning face commits the crossing + neighbor — 52 B of gather per
     crossing instead of 80 (select-in-bf16 / commit-in-f32,
     docs/DESIGN.md; cost model docs/PERF_NOTES.md "Table precision
     tiers"),
  2. exit coordinate ``s_f`` over faces with ``n_f·d_remaining > tol``
     (same crossing predicate as the reference fork's search internals;
     semantics pinned by the oracles in BASELINE.md),
  3. tally ``(s_new − s)·‖d0‖ · weight`` into the current element — the
     reference's ``EvaluateFlux`` + ``Kokkos::atomic_add``
     (PumiTallyImpl.cpp:352-380) becomes a deterministic XLA
     scatter-add,
  4. vacuum BC: a particle whose exit face has no neighbor is done and
     its position clamps to the boundary intersection point — reference
     ``ApplyVacuumBC`` (PumiTallyImpl.cpp:256-286),
  5. advance to the neighbor tet — reference ``UpdateCurrentElement``
     (PumiTallyImpl.cpp:243-254).

Lock-step waste is bounded by **active-particle compaction**: the walk
runs as a cascade of stages with halving windows. Each stage iterates
only over the first W particles; when the number of still-active
particles drops to the next window size, survivors move to the front
via a stable SORT-FREE binary partition on the done flag (counting
ranks, ops/bucketize.py — a deterministic, XLA-friendly stand-in for
the reference's stream compaction inside PUMIPic's rebuild; the
"sorted" perm mode restores the argsort-on-(done, element) variant
whose element grouping buys gather locality at argsort cost)
and the window halves. Without this, every iteration pays for the full
batch while the slowest particle finishes (reference's search loop has
the same property, SURVEY.md §3.3); with it, total work approaches
Σ(per-particle path length) instead of N × max(path length).

Tally on/off is a static flag: the initial localization pass never
tallies (reference ``is_initial_track``, PumiTallyImpl.cpp:309) and the
relocate-to-origin phase runs with weights zeroed (PumiTallyImpl.cpp:105);
here both simply compile a no-tally variant of the loop body.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from pumiumtally_tpu.ops.bucketize import (
    PARTITION_METHODS,
    partition_perm,
    unpermute,
)
from pumiumtally_tpu.mesh.tetmesh import (
    TetMesh,
    WALK_TABLE_ADJ,
    WALK_TABLE_LO_NORMALS,
    WALK_TABLE_LO_OFFSETS,
    WALK_TABLE_NORMALS,
    WALK_TABLE_OFFSETS,
)

# Smallest compaction window: below this, shrinking the batch no longer
# pays for the sort (and TPU vector units run underutilized anyway).
_MIN_WINDOW = 8192

# Kernel defaults, exported so config resolution / autotuning /
# partitioned-engine plumbing reference ONE source of truth (these have
# already been retuned from measurement once — cond_every 1→4).
COND_EVERY_DEFAULT = 4
WINDOW_FACTOR_DEFAULT = 2

# How the compaction cascade applies the survivor permutation at each
# stage boundary. "arrays"/"packed"/"indirect" produce BITWISE-identical
# results (same values, same scatter order); they differ only in how
# many random-row gathers the permutation costs — measured the largest
# cascade component on v5e (docs/PERF_NOTES.md, ~51 ms/stage at 500k
# for the per-array form):
#   "arrays"   — permute each carried array separately (8 row gathers).
#   "packed"   — pack the carry into one float [W,8] + one int [W,3]
#                row matrix and permute those (2 row gathers; same
#                trick as the packed walk table, measured ~2.6x over
#                separate gathers for the row fetch).
#   "indirect" — never permute the ray data (dest/d0/eff_w): the loop
#                gathers it per iteration through the carried original
#                slot index, and the boundary permutes only
#                s + one int [W,3] (2 small gathers, but adds a [W,8]
#                gather per walk iteration).
# All three compute the survivor permutation SORT-FREE: a stable binary
# partition on the done flag via counting ranks (ops/bucketize.py) —
# the full-capacity argsort the seed paid per stage (4.0 ms / 500k
# keys, docs/PERF_NOTES.md) is gone from the hot path.
#   "sorted"   — the pre-rank behavior: stable argsort on
#                (done, element), applied packed. Survivors are ALSO
#                grouped by element, which r2 measured worth ~1.03x in
#                gather/scatter locality — kept selectable so the chip
#                window can re-A/B locality-vs-argsort-cost. Results
#                differ from the other modes only by FP scatter order
#                (a different, equally valid permutation).
_PERM_MODES = ("arrays", "packed", "indirect", "sorted")

# The mode "auto" resolves to when PUMIUMTALLY_WALK_PERM is unset.
PERM_MODE_DEFAULT = "packed"

# Walk-table precision tiers (docs/PERF_NOTES.md "Table precision
# tiers"). "float32" is the packed single-tier table (the historical
# layout; actually the mesh's working dtype — f64 under x64).
# "bfloat16" is the two-tier form: a half-width bf16 SELECT row picks
# the exit face, then ONE full-precision refinement gather of the
# winning face's plane recomputes the crossing exactly before anything
# commits — select-in-bf16 / commit-in-f32 (docs/DESIGN.md invariant).
TABLE_DTYPES = ("float32", "bfloat16")
TABLE_DTYPE_DEFAULT = "float32"


def _resolve_table_dtype(dtype: str) -> str:
    """Resolve "auto" via the PUMIUMTALLY_WALK_TABLE_DTYPE env var.

    Mirrors ``_resolve_perm_mode``: TallyConfig.walk_kwargs() resolves
    at CONFIG time so the tier lands in the engines' static jit keys
    (an env flip recompiles instead of silently reusing the stale
    tier); a direct walk() call with table_dtype="auto" resolves at
    trace time instead.
    """
    if dtype == "auto":
        dtype = os.environ.get(
            "PUMIUMTALLY_WALK_TABLE_DTYPE", TABLE_DTYPE_DEFAULT
        )
    if dtype not in TABLE_DTYPES:
        raise ValueError(
            f"walk_table_dtype must be one of {TABLE_DTYPES} or 'auto', "
            f"got {dtype!r}"
        )
    return dtype


def _resolve_perm_mode(mode: str) -> str:
    """Resolve "auto" via the PUMIUMTALLY_WALK_PERM env var.

    Called from TallyConfig.walk_kwargs() so the resolved mode lands in
    the engines' static jit keys (an env flip then recompiles rather
    than silently reusing the stale mode). A DIRECT walk() call with
    perm_mode="auto" resolves at trace time instead — the env var is
    then read once per compilation; pass an explicit mode to A/B within
    one process.
    """
    if mode == "auto":
        mode = os.environ.get("PUMIUMTALLY_WALK_PERM", PERM_MODE_DEFAULT)
    if mode not in _PERM_MODES:
        raise ValueError(
            f"perm_mode must be one of {_PERM_MODES} or 'auto', got {mode!r}"
        )
    return mode


def score_pair(kinds, stride: int, elem, bin_off, fac, contrib, crossed):
    """One crossing group's scoring-lane update pair (docs/DESIGN.md
    "Filtered scoring"): ``sidx[w, k] = elem·stride + bin_off + k``
    (row-major ravel → particle-major, score-minor — the deterministic
    order every engine shares) and per-score values from the two
    segment bases: ``contrib`` — bitwise the flux lane's own
    ``(s_new − s)·eff_w`` update, so the track scores' factor-1 lanes
    telescope to the flux lane exactly — and ``crossed`` — the
    committed-face-crossing indicator for count scores. DROP-sentinel
    ``bin_off`` rows index past the bank and die in the scatter's
    ``mode="drop"``. Shared by the replicated walk and the partitioned
    ``walk_local`` so the lane semantics cannot drift between
    engines."""
    base = elem.astype(jnp.int32) * stride + bin_off
    sidx = base[:, None] + jnp.arange(len(kinds), dtype=jnp.int32)[None, :]
    cols = [contrib if k == "track" else crossed for k in kinds]
    return sidx, jnp.stack(cols, axis=1) * fac


def fused_tally_body(step, cond_every: int, tally: bool,
                     scoring: bool = False):
    """Build a while_loop body running ``cond_every`` masked iterations
    of ``step`` per step, fusing the group's (element, contribution)
    tally pairs into ONE scatter-add of k·W values (fewer scatter
    launches than k scatters of W; f64 impact is add-reordering only).

    ``step(*core) -> (core', pair)`` with ``pair = (elem, contrib)``
    when tallying, else None; the loop state is ``(*core, flux)``.
    Shared by the replicated walk below and the partitioned
    ``walk_local`` (parallel/partition.py) so the unroll/fuse machinery
    cannot drift between engines.

    ``scoring=True`` (implies ``tally``): pairs carry two extra
    entries ``(sidx [W,S], sval [W,S])`` from ``score_pair`` and the
    state ends ``(*core, flux, bank)`` — the group's lane updates fuse
    into ONE separate deterministic scatter-add on the bank. The flux
    scatter below is byte-for-byte the scoring-off code path, which is
    what keeps scoring-on flux bitwise.
    """
    cond_every = max(1, int(cond_every))

    def body(state):
        if scoring:
            *core, flux, bank = state
        else:
            *core, flux = state
        pairs = []
        for _ in range(cond_every):
            core, pair = step(*core)
            pairs.append(pair)
        if tally:
            if cond_every == 1:
                e0, c0 = pairs[0][0], pairs[0][1]
                flux = flux.at[e0].add(c0, mode="drop")
            else:
                flux = flux.at[jnp.concatenate([p[0] for p in pairs])].add(
                    jnp.concatenate([p[1] for p in pairs]), mode="drop"
                )
        if scoring:
            if cond_every == 1:
                si, sv = pairs[0][2].reshape(-1), pairs[0][3].reshape(-1)
            else:
                si = jnp.concatenate([p[2].reshape(-1) for p in pairs])
                sv = jnp.concatenate([p[3].reshape(-1) for p in pairs])
            bank = bank.at[si].add(sv, mode="drop")
            return (*core, flux, bank)
        return (*core, flux)

    return body


class WalkResult(NamedTuple):
    """Post-walk particle state.

    ``x`` is the committed position: the destination, clamped to the
    boundary intersection for particles that left the domain (the
    reference commits dest→origin after each search; clamp semantics at
    PumiTallyImpl.cpp:275-281, oracle test:242-245).
    ``elem`` is the final element (boundary leavers keep the last tet
    they were in, reference UpdateCurrentElement skips next==-1).
    ``s`` is the final ray coordinate along the FIXED segment
    ``x0 → dest`` (1 for particles that reached their destination,
    < 1 for boundary leavers and iteration-cap stragglers): with the
    walk's ``s_init``, a truncated particle's transport CONTINUES the
    exact original parametrization — every remaining crossing computes
    the bit-identical (s, contribution) pairs an uninterrupted walk
    would have (the sentinel straggler ladder's bitwise-recovery
    contract, round 9).
    """

    x: jnp.ndarray  # [N,3]
    elem: jnp.ndarray  # [N] int32
    done: jnp.ndarray  # [N] bool (False = walk iteration cap hit)
    exited: jnp.ndarray  # [N] bool: finished by leaving the domain (vacuum BC)
    flux: jnp.ndarray  # [E] accumulated track-length tally
    iters: jnp.ndarray  # [] int32: iterations taken
    s: jnp.ndarray = None  # [N] final ray coordinate (see above)
    # Accumulated scoring lane bank (round 10) — None unless the walk
    # was handed a ``scoring`` operand bundle.
    score_bank: jnp.ndarray = None


def _gather_walk_row(mesh: TetMesh, elem: jnp.ndarray):
    """(face_normals[N,4,3], face_offsets[N,4], face_adj[N,4]) of each
    particle's current tet — via the packed single-row gather when the
    mesh provides it."""
    if mesh.walk_table is not None:
        row = mesh.walk_table[elem]  # [N,WALK_TABLE_WIDTH]
        n = row.shape[0]
        fn = row[:, WALK_TABLE_NORMALS].reshape(n, 4, 3)
        fo = row[:, WALK_TABLE_OFFSETS]
        adj = row[:, WALK_TABLE_ADJ].astype(jnp.int32)
        return fn, fo, adj
    return mesh.face_normals[elem], mesh.face_offsets[elem], mesh.face_adj[elem]


def _resolve_lo_select(mesh, table_dtype: str) -> bool:
    """Shared entry-point guard: resolve the tier and require the
    two-tier tables when it is bf16 — ONE definition so walk() and the
    walk_xpoints replay can never diverge in resolution rule or error
    contract."""
    lo_select = _resolve_table_dtype(table_dtype) == "bfloat16"
    if lo_select and mesh.walk_table_lo is None:
        raise ValueError(
            "table_dtype='bfloat16' needs the two-tier walk tables — "
            "build the mesh with table_dtype='bfloat16' or convert it "
            "with TetMesh.with_lowp_tables()"
        )
    return lo_select


def _lift_bf16(x, fdtype):
    """bf16 → working dtype, EXACT, via the bit identity (bf16 is
    truncated f32, so the upcast is a 16-bit left shift of the bit
    pattern). Not a style choice: XLA:CPU lowers the native bf16
    convert element-at-a-time — measured ~5× the cost of the whole
    candidate einsum at bench shape, which sank the CPU A/B arm — while
    the shift form vectorizes on every backend and computes the
    identical function (pinned by the A/B's conservation equality)."""
    u = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32) << 16
    f = lax.bitcast_convert_type(u, jnp.float32)
    return f if jnp.dtype(fdtype) == jnp.float32 else f.astype(fdtype)


def select_rows_lo(row, s, dest, d0, tol, one):
    """SELECT-tier math on already-fetched (and already-lifted) rows:
    candidate crossings of all four faces from the half-width row,
    returning the per-face candidate minimum and the winning face
    index. Split out of ``select_faces_lo`` so the one-kernel Pallas
    walk (ops/pallas_walk.py), whose row fetch is a one-hot matmul
    against the streamed table block rather than a gather, runs the
    IDENTICAL selection trace — since ``_lift_bf16`` is elementwise,
    lift-then-fetch equals fetch-then-lift bitwise, and parity between
    the kernels reduces to the fetch itself."""
    n = row.shape[0]
    fn = row[:, WALK_TABLE_LO_NORMALS].reshape(n, 4, 3)
    fo = row[:, WALK_TABLE_LO_OFFSETS]
    both = jnp.einsum("nfc,nck->nfk", fn, jnp.stack([d0, dest], axis=-1))
    a = both[..., 0]  # n·d0 (bf16-rounded n)
    b = fo - both[..., 1] + a  # off − n·x0
    crossing = a * (one - s)[:, None] > tol
    s_f = jnp.where(crossing, b / jnp.where(crossing, a, one), jnp.inf)
    # Clamp-then-argmin, EXACTLY the f32 path's rule. A candidate whose
    # bf16 value lands at-or-behind the current coordinate clamps to s
    # and wins the argmin — in the common case that candidate is the
    # true exit rounded behind, and the refinement recomputes its real
    # forward crossing, so the walk stays correct. (A forward-first
    # variant that demoted clamped candidates was tried and REVERTED:
    # it broke exactly those rounded-behind true exits — 10× more
    # hull-exit drift, 4% flux divergence. The cost of keeping the
    # clamp is the rare wrong-corridor dead end documented in
    # docs/PERF_NOTES.md: a genuinely-behind BOUNDARY face can absorb
    # an exiting particle slightly inside the hull, at tie-class rate.)
    s_f = jnp.maximum(s_f, s[:, None])
    return jnp.min(s_f, axis=1), jnp.argmin(s_f, axis=1)


def select_faces_lo(table_lo, s, elem, dest, d0, tol, one):
    """bf16 SELECT tier: candidate crossings of all four faces from the
    half-width bf16 row, returning the per-face candidate minimum and
    the winning face index. Shared by the replicated walk and the
    partitioned ``walk_local`` so the selection semantics cannot drift
    between engines. The candidate values are computed in the walk's
    working dtype FROM bf16-rounded planes — the only precision lost is
    the one-time storage rounding, so two candidates must tie within
    ~bf16 epsilon before a wrong face can win (docs/PERF_NOTES.md tie
    analysis)."""
    fdtype = s.dtype
    row = _lift_bf16(
        table_lo[elem], fdtype  # [N,WALK_TABLE_LO_WIDTH] — the 32 B gather
    )
    return select_rows_lo(row, s, dest, d0, tol, one)


def refine_plane_hi(plane, s, s_sel, dest, d0, tol, one):
    """REFINEMENT-tier math on already-fetched winning-face planes
    (``[N,WALK_PLANE_WIDTH]``). Split out of ``refine_face_hi`` for the
    same reason as ``select_rows_lo``: the Pallas walk fetches the
    plane through its streamed table block and must run the identical
    refinement trace. Returns ``(s_exit, next_elem)``."""
    nw = plane[:, 0:3]
    aw = jnp.einsum("nc,nc->n", nw, d0)
    bw = plane[:, 3] - jnp.einsum("nc,nc->n", nw, dest) + aw
    genuine = aw * (one - s) > tol
    s_ref = jnp.where(genuine, bw / jnp.where(genuine, aw, one), s_sel)
    s_ref = jnp.maximum(s_ref, s)
    # No bf16 candidate at all (s_sel = inf): destination inside the
    # current tet — keep inf so the caller's reached test fires.
    s_exit = jnp.where(jnp.isinf(s_sel), s_sel, s_ref)
    return s_exit, plane[:, 4].astype(jnp.int32)


def refine_face_hi(table_hi, s, elem, f_exit, s_sel, dest, d0, tol, one):
    """Full-precision REFINEMENT tier: ONE [WALK_PLANE_WIDTH]-row
    gather (20 B) of the WINNING face recomputes its crossing exactly —
    so track lengths and committed positions carry working-dtype
    accuracy — and yields that face's neighbor id from the row's adj
    lane (exact within the checked id limit), so no separate adjacency
    gather or take-along-axis runs per crossing. Returns
    ``(s_exit, next_elem)``. A face the full-precision predicate no
    longer recognizes as a forward crossing (only possible within
    tolerance of parallel — the bf16 candidacy flipped it) keeps its
    bf16 candidate value: that is exactly what a pure low-precision
    walk would commit, and the max(s) clamp still forbids backward
    steps."""
    plane = table_hi[elem * 4 + f_exit]  # [N,WALK_PLANE_WIDTH]
    return refine_plane_hi(plane, s, s_sel, dest, d0, tol, one)


def _advance_geometry(mesh, s, elem, dest, d0, tol, one, lo_select=False):
    """The per-step crossing geometry shared by ``walk`` and the
    ``walk_xpoints`` debug replay — ONE definition so the replay can
    never diverge from the transport it reconstructs.

    Both ray projections are against walk-constant vectors
    (x0 = dest − d0, so off − n·x0 = off − n·dest + n·d0). The crossing
    predicate tests the REMAINING segment (n·d_rem > tol), matching the
    reference's per-step test exactly; the max(s) clamp keeps a
    committed point that sits epsilon-outside a face from stepping
    backwards. ``reached`` covers a destination inside the current tet
    and the no-forward-crossing corner (zero-length segment).

    ``lo_select`` switches to the two-tier path: face selection from
    the mesh's bf16 select tier, then ONE full-precision refinement row
    of the winning face commits the crossing AND supplies its neighbor
    id from the row's float adj lane (exact within the checked id
    ceiling — ``face_adj`` is never gathered here). Select-in-bf16 /
    commit-in-f32, docs/DESIGN.md."""
    if lo_select:
        s_sel, f_exit = select_faces_lo(
            mesh.walk_table_lo, s, elem, dest, d0, tol, one
        )
        s_exit, next_elem = refine_face_hi(
            mesh.walk_table_hi, s, elem, f_exit, s_sel, dest, d0, tol, one
        )
    else:
        fn, fo, adj = _gather_walk_row(mesh, elem)
        both = jnp.einsum("nfc,nck->nfk", fn, jnp.stack([d0, dest], axis=-1))
        a = both[..., 0]  # n·d0
        b = fo - both[..., 1] + a  # off − n·x0
        crossing = a * (one - s)[:, None] > tol
        s_f = jnp.where(crossing, b / jnp.where(crossing, a, one), jnp.inf)
        s_f = jnp.maximum(s_f, s[:, None])
        s_exit = jnp.min(s_f, axis=1)
        f_exit = jnp.argmin(s_f, axis=1)
        next_elem = jnp.take_along_axis(adj, f_exit[:, None], axis=1)[:, 0]
    reached = s_exit >= one
    s_new = jnp.where(reached, one, s_exit)
    hit_boundary = (~reached) & (next_elem == -1)
    return s_new, reached, next_elem, hit_boundary


def walk(
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dest: jnp.ndarray,
    in_flight: jnp.ndarray,
    weight: jnp.ndarray,
    flux: jnp.ndarray,
    *,
    tally: bool,
    tol: float,
    max_iters: int,
    compact: bool = True,
    min_window: int = _MIN_WINDOW,
    cond_every: int = COND_EVERY_DEFAULT,
    window_factor: int = WINDOW_FACTOR_DEFAULT,
    perm_mode: str = "auto",
    partition_method: str = "rank",
    table_dtype: str = "auto",
    s_init: jnp.ndarray = None,
    scoring=None,
    tally_seg: jnp.ndarray = None,
) -> WalkResult:
    """Walk every particle from ``x`` (inside ``elem``) toward ``dest``.

    Particles with ``in_flight == 0`` must be given ``dest == x`` by the
    caller (hold position — reference PumiTallyImpl.cpp:100-103); they
    finish on the first iteration with zero tally contribution
    (EvaluateFlux skips them, PumiTallyImpl.cpp:364).

    ``cond_every`` unrolls that many masked body iterations per
    ``while_loop`` step, evaluating the all-done reduction once per
    group instead of per crossing — done particles are inert under the
    active mask, so extra unrolled iterations change no result, only
    waste at most ``cond_every − 1`` window passes per stage exit (and
    the iteration budget may overshoot by the same amount before the
    "not found" warning fires). Default 4: measured best on v5e
    (docs/PERF_NOTES.md round-2 sweep).

    The loop carry is deliberately minimal — it is also the payload the
    compaction cascade must permute at every stage boundary (measured
    a major cascade cost, docs/PERF_NOTES.md): the in-flight flag,
    weight and segment length fold into ONE premultiplied tally weight
    ``eff_w = flying·weight·‖d0‖`` (the only place any of them is read),
    the start position is re-derived from ``dest − d0``, and the exited
    mask is recovered post-loop as ``done & (s < 1)`` (a boundary exit
    always strictly precedes the destination; reaching it exactly
    commits ``s = 1``).

    ``perm_mode`` picks how the cascade applies the stage-boundary
    permutation (see ``_PERM_MODES``) — "arrays"/"packed"/"indirect"
    are bitwise equivalent (sort-free binary done-partition); "sorted"
    restores the element-locality argsort (FP-equal only); "auto"
    resolves via ``PUMIUMTALLY_WALK_PERM`` (default "packed").
    ``window_factor`` is the cascade's window shrink ratio (2 →
    halving; larger → fewer, coarser stages — fewer boundary
    permutations at the cost of more lock-step waste).

    ``partition_method`` selects how the sort-free modes compute the
    survivor permutation: "rank" (counting ranks, the default) or
    "argsort" (the seed's stable sort over the same binary key) — both
    produce the IDENTICAL permutation, so results are bitwise equal;
    the knob exists for parity tests and on-chip A/B
    (tools/exp_partition_ab.py).

    ``table_dtype`` selects the walk-table precision tier
    (``TABLE_DTYPES``): "float32" gathers the packed single-tier row;
    "bfloat16" selects the exit face from the mesh's bf16 tier and
    refines only the winning face at full precision (NOT bitwise vs
    the f32 tier — wrong-face selection on sub-bf16-epsilon ties is
    the documented benign divergence; conservation is preserved by the
    s-telescoping tally). "auto" resolves via
    ``PUMIUMTALLY_WALK_TABLE_DTYPE`` (default "float32").

    ``scoring`` (a ``scoring.ScoreOps``, tally walks only) arms the
    segment-commit scoring hook: at every crossing the group's lane
    updates (``score_pair``) fuse into ONE deterministic scatter-add
    on the bundle's flattened bank, returned as
    ``WalkResult.score_bank``. The per-particle bin offsets and
    factor rows are walk-constant: the cascade never permutes them —
    each stage gathers its window's rows ONCE through the carried
    original-slot index. The flux scatter is the byte-identical
    scoring-off path, so scoring-on flux stays bitwise.

    ``tally_seg`` (tally walks only) is the SEGMENTED-commit hook
    (round 12, the cross-session fusion scatter-back): a per-particle
    walk-constant int32 offset added to every flux scatter index, so a
    slab packing K independent particle populations tallies into a
    concatenated ``[K·E]`` flux bank — segment k's particles commit at
    ``k·E + elem`` and never touch another segment's lanes (dead
    padding rows carry an offset at/past the bank end and die in the
    scatter's ``mode="drop"``, exactly like the scoring DROP
    sentinel). The rows ride the walk like the scoring rows: never
    permuted by the cascade, gathered per stage through the carried
    original-slot index. ``None`` (every non-fused path) leaves the
    trace byte-identical to pre-hook builds. Per-segment determinism:
    a segment's particles keep their relative row order through every
    stable stage partition, so each bank segment accumulates the
    bit-identical addition sequence a solo walk of that segment
    commits (docs/DESIGN.md "Cross-session fusion").
    """
    lo_select = _resolve_lo_select(mesh, table_dtype)
    score_on = scoring is not None
    if score_on and not tally:
        raise ValueError("scoring requires a tallying walk (tally=True)")
    seg_on = tally_seg is not None
    if seg_on and not tally:
        raise ValueError("tally_seg requires a tallying walk (tally=True)")
    if score_on:
        s_kinds = scoring.kinds
        # Lanes per element — static (shape-derived) like every other
        # piece of the hook; the bank length is a multiple of [E].
        s_stride = scoring.bank.shape[0] // flux.shape[0]
        sb0, sf0, bank = scoring.bin_off, scoring.fac, scoring.bank
    fdtype = x.dtype
    n_total = x.shape[0]
    one = jnp.asarray(1.0, fdtype)
    # All-False initial done mask, derived from an input so it carries
    # the same sharding/varying-axis type as the particle arrays when
    # this runs inside shard_map (a literal zeros() constant would be
    # "unvarying" and break the while_loop carry typing).
    done0 = in_flight != in_flight
    d0 = dest - x  # the whole walk's segment; s parametrizes along it
    seg_len = jnp.linalg.norm(d0, axis=1)  # computed once, not per iter
    # ``s_init`` continues an interrupted walk's EXACT parametrization
    # (the caller passes the previous WalkResult.s together with the
    # ORIGINAL x/dest, so d0 — and with it every remaining crossing's
    # arithmetic — is bit-identical to the uninterrupted walk). None
    # (every production path) keeps the historical fresh-ray start.
    s0 = (
        jnp.zeros_like(seg_len) if s_init is None
        else s_init.astype(fdtype)
    )
    # flying/weight/seg_len enter the loop only through the tally
    # contribution — premultiply once (f64 parity: associativity-only
    # change, ~1 ulp).
    eff_w = jnp.where(in_flight.astype(bool), weight * seg_len, 0.0)

    def advance(s, elem, dest, d0, eff_w, done, sb=None, sf=None,
                tseg=None):
        """One lock-step iteration over a (possibly windowed) batch.
        Returns the advanced (s, elem, done) plus this crossing's tally
        pair (element indexed, contribution) — the caller decides how
        to scatter (per iteration, or fused across an unrolled group).
        ``sb``/``sf`` (scoring only) are the window's walk-constant bin
        offsets / factor rows; the pair then carries the lane update
        too (``score_pair``). ``tseg`` (segmented commit only) is the
        window's walk-constant flux-index offset rows: the pair's
        element index becomes ``elem + tseg`` — the scoring ``sidx``
        stays un-offset because the fused bank offset rides in the
        caller's pre-shifted ``bin_off`` rows."""
        active = ~done
        s_new, reached, next_elem, hit_boundary = _advance_geometry(
            mesh, s, elem, dest, d0, tol, one, lo_select
        )

        if tally:
            contrib = jnp.where(active, (s_new - s) * eff_w, 0.0)
            eidx = elem if tseg is None else elem + tseg
            if score_on:
                crossed = (active & ~reached).astype(contrib.dtype)
                sidx, sval = score_pair(
                    s_kinds, s_stride, elem, sb, sf, contrib, crossed
                )
                pair = (eidx, contrib, sidx, sval)
            else:
                pair = (eidx, contrib)
        else:
            pair = None

        moving = active & ~reached & ~hit_boundary
        elem = jnp.where(moving, next_elem, elem)
        s = jnp.where(active, s_new, s)
        done = done | reached | hit_boundary
        return (s, elem, done), pair

    def step(it, s, elem, dest, d0, eff_w, done):
        (s, elem, done), pair = advance(
            s, elem, dest, d0, eff_w, done,
            sb0 if score_on else None, sf0 if score_on else None,
            tally_seg if seg_on else None,
        )
        return (it + 1, s, elem, dest, d0, eff_w, done), pair

    it0 = jnp.asarray(0, jnp.int32)
    # NOTE: valid for FULL-batch loops only when scoring/segmentation
    # is armed (the step closes over the full-size sb0/sf0/tally_seg);
    # the cascade builds per-stage bodies with windowed rows instead.
    body = fused_tally_body(step, cond_every, tally, scoring=score_on)

    def final_x(s, done, exited, dest, d0):
        """Materialize positions from the ray coordinate — exactly once.
        Particles that reached their destination commit ``dest``
        bit-exactly (the continue-mode contract: next move's origins
        equal the committed positions); boundary leavers commit the
        clamped intersection point ``x0 + s·d0 = dest + (s−1)·d0``."""
        return jnp.where(
            (done & ~exited)[:, None], dest, dest + (s - one)[:, None] * d0
        )

    if partition_method not in PARTITION_METHODS:
        raise ValueError(
            f"partition_method must be one of {PARTITION_METHODS}, "
            f"got {partition_method!r}"
        )
    min_window = max(1, min_window)
    # Position of ``done`` from the END of the loop state: the bank
    # rides after flux when scoring is armed.
    dpos = -3 if score_on else -2
    if not compact or n_total <= min_window:
        def cond(state):
            it = state[0]
            done = state[dpos]
            return (it < max_iters) & jnp.any(~done)

        carry = (it0, s0, elem, dest, d0, eff_w, done0, flux)
        if score_on:
            it, s, elem, _, _, _, done, flux, bank = lax.while_loop(
                cond, body, carry + (bank,)
            )
        else:
            it, s, elem, _, _, _, done, flux = lax.while_loop(
                cond, body, carry
            )
        exited = done & (s < one)
        return WalkResult(
            x=final_x(s, done, exited, dest, d0), elem=elem, done=done,
            exited=exited, flux=flux, iters=it, s=s,
            score_bank=bank if score_on else None,
        )

    # ---- compaction cascade --------------------------------------------
    # Static window schedule: N, N/f, …, down to min_window.
    factor = int(window_factor)
    if factor < 2:
        raise ValueError(
            f"window_factor must be >= 2, got {window_factor!r} "
            "(use compact=False to disable the cascade)"
        )
    windows = [n_total]
    while windows[-1] > min_window:
        windows.append(max(min_window, -(-windows[-1] // factor)))

    # Original slot of the particle currently in each row, so the
    # compaction permutations can be undone at the end (and, in
    # "indirect" mode, so the loop can reach the never-permuted ray
    # data).
    idx = jnp.cumsum(jnp.ones_like(elem)) - 1  # iota, varying under shard_map

    mode = _resolve_perm_mode(perm_mode)
    imax = jnp.iinfo(jnp.int32).max
    cat = lambda h, a, w: jnp.concatenate([h, a[w:]], axis=0)  # noqa: E731

    if mode == "indirect":
        # Ray data packed ONCE, in original slot order, never permuted:
        # the loop gathers each window row through `idx`. Padded to 8
        # columns so the row stride stays power-of-two-aligned.
        ray = jnp.concatenate(
            [dest, d0, eff_w[:, None], jnp.zeros_like(eff_w)[:, None]],
            axis=1,
        )  # [N,8]

    s = s0
    done = done0
    it = it0
    for si, w in enumerate(windows):
        nxt = windows[si + 1] if si + 1 < len(windows) else 0

        def cond(state, _nxt=nxt):
            it = state[0]
            done = state[dpos]
            n_active = jnp.sum(~done)
            return (it < max_iters) & (n_active > _nxt)

        head = lambda a, _w=w: a[:_w]  # noqa: E731 — static-size window slice
        if score_on:
            # Scoring rows are walk-constant and NEVER permuted: gather
            # this stage's window ONCE through the carried original-slot
            # index (loop-invariant closures — one [w] + [w,S] gather
            # per stage, zero changes to the permutation machinery).
            sb_w, sf_w = sb0[head(idx)], sf0[head(idx)]
        else:
            sb_w = sf_w = None
        # Segment-offset rows ride exactly like the scoring rows: one
        # [w] gather per stage through the original-slot index.
        seg_w = tally_seg[head(idx)] if seg_on else None
        if mode == "indirect":
            idx_w = head(idx)

            def step_ind(it, s, elem, done, _idx=idx_w, _sb=sb_w,
                         _sf=sf_w, _tg=seg_w):
                r = ray[_idx]
                (s, elem, done), pair = advance(
                    s, elem, r[:, 0:3], r[:, 3:6], r[:, 6], done, _sb,
                    _sf, _tg,
                )
                return (it + 1, s, elem, done), pair

            body_i = fused_tally_body(step_ind, cond_every, tally,
                                      scoring=score_on)
            carry_i = (it, head(s), head(elem), head(done), flux)
            if score_on:
                it, sh, eh, dh, flux, bank = lax.while_loop(
                    cond, body_i, carry_i + (bank,)
                )
            else:
                it, sh, eh, dh, flux = lax.while_loop(
                    cond, body_i, carry_i
                )
        else:
            if score_on or seg_on:
                def step_w(it, s, elem, dest, d0, eff_w, done, _sb=sb_w,
                           _sf=sf_w, _tg=seg_w):
                    (s, elem, done), pair = advance(
                        s, elem, dest, d0, eff_w, done, _sb, _sf, _tg
                    )
                    return (it + 1, s, elem, dest, d0, eff_w, done), pair

                body_w = fused_tally_body(step_w, cond_every, tally,
                                          scoring=score_on)
            else:
                body_w = body
            carry_w = (
                it, head(s), head(elem), head(dest), head(d0),
                head(eff_w), head(done), flux,
            )
            if score_on:
                it, sh, eh, _, _, _, dh, flux, bank = lax.while_loop(
                    cond, body_w, carry_w + (bank,)
                )
            else:
                it, sh, eh, _, _, _, dh, flux = lax.while_loop(
                    cond, body_w, carry_w
                )
        # NOTE: these window write-backs deliberately use concatenate,
        # NOT `a.at[:w].set(a[:w][perm])`: the in-place form miscompiles
        # under jit when the dynamic-update-slice is fused with a gather
        # reading the same buffer (observed on the CPU backend,
        # jax 0.8.x — duplicated/missing rows). Concatenate forces a
        # fresh result buffer and costs the same copy.
        if nxt:
            # Survivors move to the front, stably. Default modes: a
            # SORT-FREE binary partition on the done flag — counting
            # ranks reproduce the stable-argsort permutation of that
            # flag exactly (ops/bucketize.py), so no argsort runs in
            # the hot path. "sorted" keeps the seed's stable argsort on
            # (done, current element): survivors are also grouped by
            # element, buying gather/scatter locality at argsort cost.
            # Only rows [:w] can be active, so partitioning the window
            # alone suffices and the cost shrinks with the cascade.
            if mode == "sorted":
                key = jnp.where(dh, imax, eh)
                perm = jnp.argsort(key, stable=True)
            else:
                perm, _, _ = partition_perm(
                    dh.astype(jnp.int32), 2, method=partition_method
                )
            if mode == "arrays":
                # Round-2 form: one row gather per carried array.
                upd = lambda a, h, _p=perm, _w=w: cat(h[_p], a, _w)  # noqa: E731
                s = upd(s, sh)
                elem = upd(elem, eh)
                done = upd(done, dh)
                dest = upd(dest, dest[:w])
                d0 = upd(d0, d0[:w])
                eff_w = upd(eff_w, eff_w[:w])
                idx = upd(idx, idx[:w])
            else:
                ipack = jnp.stack(
                    [eh, idx[:w], dh.astype(jnp.int32)], axis=1
                )[perm]  # [w,3] — one row gather for all int carries
                elem = cat(ipack[:, 0], elem, w)
                idx = cat(ipack[:, 1], idx, w)
                done = cat(ipack[:, 2].astype(bool), done, w)
                if mode == "indirect":
                    s = cat(sh[perm], s, w)
                else:  # "packed" / "sorted"
                    fpack = jnp.concatenate(
                        [sh[:, None], dest[:w], d0[:w], eff_w[:w, None]],
                        axis=1,
                    )[perm]  # [w,8] — one row gather for all float carries
                    s = cat(fpack[:, 0], s, w)
                    dest = cat(fpack[:, 1:4], dest, w)
                    d0 = cat(fpack[:, 4:7], d0, w)
                    eff_w = cat(fpack[:, 7], eff_w, w)
        else:
            s = cat(sh, s, w)
            elem = cat(eh, elem, w)
            done = cat(dh, done, w)

    # Undo the accumulated permutation: row i holds original slot
    # idx[i], so a direct scatter by idx restores slot order — the
    # ``argsort(idx)`` + gather the seed paid here collapses to one
    # scatter (bitwise identical: the same inverse permutation).
    if mode == "indirect":
        # dest/d0 were never permuted — restore the particle carries to
        # original order and materialize positions there directly.
        s = unpermute(s, idx)
        elem = unpermute(elem, idx)
        done = unpermute(done, idx)
        exited = done & (s < one)
        return WalkResult(
            x=final_x(s, done, exited, dest, d0), elem=elem, done=done,
            exited=exited, flux=flux, iters=it, s=s,
            score_bank=bank if score_on else None,
        )
    exited = done & (s < one)
    x_fin = final_x(s, done, exited, dest, d0)
    return WalkResult(
        x=unpermute(x_fin, idx), elem=unpermute(elem, idx),
        done=unpermute(done, idx), exited=unpermute(exited, idx),
        flux=flux, iters=it, s=unpermute(s, idx),
        score_bank=bank if score_on else None,
    )


def walk_xpoints(
    mesh: TetMesh,
    x: jnp.ndarray,
    elem: jnp.ndarray,
    dest: jnp.ndarray,
    in_flight: jnp.ndarray,
    *,
    tol: float,
    max_iters: int,
    table_dtype: str = "auto",
) -> jnp.ndarray:
    """Replay a transport and return each particle's LAST
    face-intersection point — the reference's white-box debug surface
    (``getIntersectionPoints()``, PumiTallyImpl.h:177-178: the
    ``inter_points`` buffer holds the location of the last intersected
    face, initialized to the particle's starting position, and is
    updated at every crossing including the boundary exit).

    A particle that reaches its destination inside its starting element
    (or does not fly) keeps its starting position as its xpoint. No
    tally, no compaction — this is an inspection path, not a hot path;
    the production walk's s-parametrization deliberately discards the
    per-crossing positions this reconstructs.
    """
    # The replay must run the SAME tier as the transport it
    # reconstructs (shared resolution + missing-tables guard).
    lo_select = _resolve_lo_select(mesh, table_dtype)
    fdtype = x.dtype
    one = jnp.asarray(1.0, fdtype)
    is_flying = in_flight[:, None] == 1
    dest = jnp.where(is_flying, dest, x)  # stopped -> hold
    d0 = dest - x
    s0 = jnp.zeros((x.shape[0],), fdtype)
    done0 = in_flight != in_flight

    def cond(state):
        it, _s, _elem, done, _sc = state
        return (it < max_iters) & jnp.any(~done)

    def body(state):
        it, s, elem, done, s_cross = state
        active = ~done
        s_new, reached, next_elem, hit_boundary = _advance_geometry(
            mesh, s, elem, dest, d0, tol, one, lo_select
        )
        # A face was intersected this step (interior crossing OR the
        # boundary exit) -> record its location's ray coordinate.
        s_cross = jnp.where(active & ~reached, s_new, s_cross)
        moving = active & ~reached & ~hit_boundary
        elem = jnp.where(moving, next_elem, elem)
        s = jnp.where(active, s_new, s)
        done = done | reached | hit_boundary
        return it + 1, s, elem, done, s_cross

    _it, _s, _elem, _done, s_cross = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), s0, elem, done0, s0)
    )
    return x + s_cross[:, None] * d0
