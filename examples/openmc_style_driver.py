"""An OpenMC-style host driver, end to end.

Models how a physics code drives the tally (the reference's OpenMC
integration calls the constructor in openmc_init, the localization in
initialize_batch, the moves in process_advance_particle_events, and the
write in openmc_run — reference README.md:84-104 and the SVG call map):
sample sources, localize, run transport "batches" where each step hands
origins/destinations/flags/weights to the tally, then write VTK.

Run:  python examples/openmc_style_driver.py [--mode mono|stream|part]
          [--protocol fast|reference]

--protocol reference passes origins on EVERY move exactly as the
reference's host does (PumiTallyImpl.cpp:66-149); the engine's
auto_continue detects the echoes and skips the redundant uploads, so
it costs the same as the explicit origins=None fast path. Partitioned
mode writes rank-aware .pvtu pieces.

The transport physics here is a stand-in random walk; swap in a real
physics code by replacing `sample_step`.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The conservation check below compares ~240k accumulated f64 segment
# lengths; run the engine in f64 too (as the parity test suite does) so
# the 1e-6 assertion is meaningful on any backend.
import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from pumiumtally_tpu import (  # noqa: E402
    PartitionedPumiTally,
    PumiTally,
    StreamingTally,
    TallyConfig,
    build_box,
)

N = 20_000
BATCHES = 3
STEPS_PER_BATCH = 4


def sample_step(rng, pos):
    """Next flight destinations + per-particle weights (physics stand-in)."""
    d = pos + rng.normal(scale=0.15, size=pos.shape)
    return np.clip(d, 0.01, 0.99), rng.uniform(0.5, 1.5, pos.shape[0])


def make_tally(mode: str, mesh, vmem_bound=None):
    if mode == "stream":
        return StreamingTally(mesh, N, chunk_size=8192)
    if mode == "part":
        from pumiumtally_tpu.parallel import make_device_mesh

        return PartitionedPumiTally(
            mesh, N,
            TallyConfig(device_mesh=make_device_mesh(), capacity_factor=4.0,
                        walk_vmem_max_elems=vmem_bound),
        )
    return PumiTally(mesh, N)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["mono", "stream", "part"],
                    default="mono")
    ap.add_argument("--protocol", choices=["fast", "reference"],
                    default="fast",
                    help="reference = origins passed every move (the "
                         "host-side echo is deduped automatically)")
    ap.add_argument("--vmem-bound", type=int, default=None,
                    help="part mode: per-chip element bound for the "
                         "VMEM one-hot walk (oversized partitions "
                         "sub-split into blocks; see "
                         "TallyConfig.walk_vmem_max_elems)")
    args = ap.parse_args()

    mesh = build_box(1.0, 1.0, 1.0, 8, 8, 8)  # stand-in for mesh.osh
    tally = make_tally(args.mode, mesh, vmem_bound=args.vmem_bound)
    rng = np.random.default_rng(0)

    total_expected = 0.0
    for batch in range(BATCHES):
        # New batch: resample every source (so the first move passes
        # explicit origins — the reference's phase-A relocation path).
        pos = rng.uniform(0.05, 0.95, (N, 3))
        tally.CopyInitialPosition(pos.reshape(-1).copy())
        origins = pos
        for step in range(STEPS_PER_BATCH):
            dests, weights = sample_step(rng, origins)
            flying = np.ones(N, np.int8)
            if step == 0 or args.protocol == "reference":
                # Reference protocol: origins passed every call. After
                # step 0 they echo the committed positions, so
                # auto_continue skips the upload + phase A.
                tally.MoveToNextLocation(
                    origins.reshape(-1).copy(), dests.reshape(-1).copy(),
                    flying, weights,
                )
            else:
                # Continuing particles: the fast path skips phase A.
                tally.MoveToNextLocation(
                    None, dests.reshape(-1).copy(), flying, weights,
                )
            assert flying.sum() == 0  # zeroed in place, per the protocol
            total_expected += float(
                (np.linalg.norm(dests - origins, axis=1) * weights).sum()
            )
            origins = dests
        print(f"batch {batch}: done")

    got = float(np.asarray(tally.flux).sum())
    rel = abs(got - total_expected) / total_expected
    print(f"sum(flux) = {got:.4f}  analytic = {total_expected:.4f}  "
          f"rel err = {rel:.2e}")
    if args.protocol == "reference":
        print(f"origin uploads deduped: {tally.auto_continue_hits} "
              f"of {BATCHES * STEPS_PER_BATCH} moves")
    assert rel < 1e-6
    out = "fluxresult.pvtu" if args.mode == "part" else "fluxresult.vtk"
    tally.WriteTallyResults(out)
    print(f"wrote {out} ({args.mode} mode)")


if __name__ == "__main__":
    main()
