"""Multi-chip partitioned run with autotuning, checkpointing, and
rank-aware output — the features a long physics campaign combines.

Runs anywhere: on a TPU pod slice the device mesh spans real chips; on
a CPU dev box set
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
to simulate 8 devices (how the test suite runs all multi-chip paths).

Flow:
  1. build (or load) a mesh and autotune the walk kernel for this
     backend,
  2. transport batches on the partitioned engine (mesh sharded over
     the chips, particles migrating at partition faces),
  3. checkpoint mid-campaign; restore into a FRESH engine and continue
     (checkpoints are canonical — any engine kind can resume them),
  4. write a rank-aware multi-piece .pvtu.
"""

import numpy as np

from pumiumtally_tpu import (
    PartitionedPumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh
from pumiumtally_tpu.utils import (
    autotune_walk,
    load_tally_state,
    save_tally_state,
)

N = 20_000
MOVES_BEFORE, MOVES_AFTER = 2, 2


def transport(tally, prev, moves, rng):
    for _ in range(moves):
        dst = np.clip(prev + rng.normal(scale=0.2, size=prev.shape),
                      0.02, 0.98)
        tally.MoveToNextLocation(prev.reshape(-1).copy(),
                                 dst.reshape(-1).copy(),
                                 np.ones(len(prev), np.int8),
                                 np.ones(len(prev)))
        prev = dst
    return prev


def main() -> None:
    mesh = build_box(1.0, 1.0, 1.0, 8, 8, 8)  # 3072 tets
    dm = make_device_mesh()  # every visible device

    # 1. measure the walk knobs for THIS backend (seconds, done once
    #    per deployment; tuning cannot change physics).
    tuned, report = autotune_walk(mesh, n_particles=min(N, 50_000), moves=2)
    print(f"autotuned: {dict(tuned.walk_kwargs()) or 'defaults win'}")

    cfg = TallyConfig(
        device_mesh=dm,
        capacity_factor=3.0,
        walk_cond_every=tuned.walk_cond_every,
        walk_min_window=tuned.walk_min_window,
    )
    t = PartitionedPumiTally(mesh, N, cfg)

    rng = np.random.default_rng(0)
    src = rng.uniform(0.05, 0.95, (N, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())

    # 2. first half of the campaign
    prev = transport(t, src, MOVES_BEFORE, rng)

    # 3. checkpoint; resume in a FRESH engine (same mesh + N required;
    #    the engine kind need not match the saver's).
    save_tally_state(t, "campaign.npz")
    t2 = PartitionedPumiTally(mesh, N, cfg)
    load_tally_state(t2, "campaign.npz")
    transport(t2, prev, MOVES_AFTER, rng)

    # 4. one .vtu piece per chip + the .pvtu index
    t2.WriteTallyResults("flux_result.pvtu")
    print("wrote flux_result.pvtu (+ per-chip pieces)")


if __name__ == "__main__":
    main()
