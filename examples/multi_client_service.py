"""Two concurrent OpenMC-style drivers sharing one tally server.

The multi-session service (pumiumtally_tpu/service) owns the device;
each driver attaches as an independent session with its OWN facade,
flux, and batch statistics — the serving-layer counterpart of
examples/openmc_style_driver.py's single-client loop. The two client
threads below submit moves concurrently; the service's deficit-round-
robin scheduler interleaves them on the device, and the double-
buffered staging layer means neither client ever blocks on the
other's device compute (futures resolve in submission order).

The contract this example then CHECKS is the service's core
invariant — determinism under concurrency: after both concurrent
campaigns finish, each session's flux is asserted BITWISE identical
to a serial single-client run of the same campaign on a bare facade.
Multi-tenancy costs accuracy nothing, not even rounding.

Run:  python examples/multi_client_service.py
"""

import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Bitwise assertions are meaningful in any dtype, but run f64 like the
# parity suites (and the sibling example) so the conservation check
# below is tight on every backend.
import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

from pumiumtally_tpu import (  # noqa: E402
    PumiTally,
    ServiceBusyError,
    TallyService,
    build_box,
)

N = 10_000
BATCHES = 2
STEPS_PER_BATCH = 3
CLIENTS = {"alice": 7, "bob": 8}  # session id -> rng seed


def campaign(seed):
    """One driver's full deterministic trajectory (sources +
    destinations + weights per batch) — both the concurrent and the
    serial runs replay exactly this."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(BATCHES):
        src = rng.uniform(0.05, 0.95, (N, 3))
        steps = []
        pos = src
        for _ in range(STEPS_PER_BATCH):
            dest = np.clip(pos + rng.normal(scale=0.15, size=pos.shape),
                           0.01, 0.99)
            steps.append((dest, rng.uniform(0.5, 1.5, N)))
            pos = dest
        out.append((src, steps))
    return out


def drive_session(handle, work):
    """An OpenMC-style client loop against the service: submit a
    batch's staged moves, retry on backpressure, wait at the batch
    boundary. The caller's buffers are recycled immediately — staging
    copied them out at submit."""
    def submit(fn, *args, **kw):
        while True:
            try:
                return fn(*args, **kw)
            except ServiceBusyError:
                # Queue full: an earlier move is still walking.
                time.sleep(0.001)
    for src, steps in work:
        futures = [submit(handle.copy_initial_position,
                          src.reshape(-1).copy())]
        for dest, weights in steps:
            futures.append(submit(
                handle.move, None, dest.reshape(-1).copy(),
                np.ones(N, np.int8), weights.copy(),
            ))
        for f in futures:
            f.result(timeout=600)


def drive_direct(tally, work):
    """The serial single-client reference: the same campaign on a bare
    facade."""
    for src, steps in work:
        tally.CopyInitialPosition(src.reshape(-1).copy())
        for dest, weights in steps:
            tally.MoveToNextLocation(None, dest.reshape(-1).copy(),
                                     np.ones(N, np.int8), weights.copy())


def main():
    mesh = build_box(1.0, 1.0, 1.0, 8, 8, 8)
    with TallyService() as service:
        handles = {
            name: service.open_session(PumiTally(mesh, N),
                                       session_id=name)
            for name in CLIENTS
        }
        threads = [
            threading.Thread(target=drive_session,
                             args=(handles[name], campaign(seed)),
                             name=name)
            for name, seed in CLIENTS.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        served = {
            name: handles[name].flux().result(timeout=600)
            for name in CLIENTS
        }

    for name, seed in CLIENTS.items():
        solo = PumiTally(mesh, N)
        drive_direct(solo, campaign(seed))
        match = np.array_equal(served[name], np.asarray(solo.flux))
        total = float(served[name].sum())
        print(f"session {name}: sum(flux) = {total:.4f}  "
              f"bitwise vs serial run: {match}")
        assert match, f"{name}: concurrent flux diverged from serial"
    print(f"{len(CLIENTS)} concurrent clients, one device, "
          "zero cross-talk: every session bitwise-identical to its "
          "serial run")


if __name__ == "__main__":
    main()
