"""On-chip de-risk + bench of the PRODUCTION VMEM walk kernel
(ops/vmem_walk.py — promoted from the tools/exp_r3_vmem.py prototype).

Three stages, each reported even if a later one fails:
  1. COMPILE: Mosaic-lower vmem_walk_local (interpret=False) on the
     attached accelerator — the round-3 verdict's open risk.
  2. PARITY: compare against walk_local on the same workload (f32
     tolerances; elem/pending equality away from face ties).
  3. BENCH: rate sweep over partition sizes L and the w_tile knob,
     against the gather-based walk_local baseline.
  4. ENGINE: PartitionedPumiTally with walk_vmem_max_elems set, on a
     1-device mesh over the real chip (sanity + rate).

Usage:  python tools/exp_r4_vmem_compile.py [n_particles]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.vmem_walk import vmem_walk_local
from pumiumtally_tpu.parallel.partition import build_partition, walk_local


def chip_workload(divs, ndev, n, seed=0):
    mesh = build_box(1, 1, 1, divs, divs, divs, dtype=jnp.float32)
    part = build_partition(mesh, ndev)
    rng = np.random.default_rng(seed)
    chip = 0
    table = part.table[chip * part.L: (chip + 1) * part.L]
    orig = np.asarray(part.orig_of_glid).reshape(ndev, part.L)[chip]
    owned = np.flatnonzero(orig >= 0)
    lelem = rng.choice(owned, size=n).astype(np.int32)
    coords = np.asarray(mesh.coords)
    tets = np.asarray(mesh.tet2vert)
    cent = coords[tets[orig[lelem]]].mean(axis=1).astype(np.float32)
    dest = (cent + rng.normal(scale=0.2, size=(n, 3))).astype(np.float32)
    return part, (
        jnp.asarray(table), jnp.asarray(cent), jnp.asarray(lelem),
        jnp.asarray(dest), jnp.ones(n, jnp.int8),
        jnp.ones(n, jnp.float32), jnp.zeros(n, bool), jnp.zeros(n, bool),
        jnp.zeros(part.L, jnp.float32),
    )


def main(n: int) -> None:
    print(f"backend={jax.default_backend()} devices={jax.devices()}")

    # -- 1. compile-only ---------------------------------------------------
    part, args = chip_workload(divs=6, ndev=2, n=4096)
    try:
        t0 = time.perf_counter()
        out = vmem_walk_local(*args, tally=True, tol=1e-6, max_iters=2048,
                              interpret=False)
        jax.block_until_ready(out)
        print(f"COMPILE OK in {time.perf_counter() - t0:.1f}s "
              f"(L={part.L})")
    except Exception as e:  # noqa: BLE001 — the experiment's question
        print(f"COMPILE FAILED: {type(e).__name__}: {str(e)[:500]}")
        return

    # -- 2. parity ---------------------------------------------------------
    ref = walk_local(*args, tally=True, tol=1e-6, max_iters=2048)
    mism = float(np.mean(np.asarray(out[1]) != np.asarray(ref[1])))
    fdiff = float(np.max(np.abs(np.asarray(out[5]) - np.asarray(ref[5]))))
    pend_mism = float(np.mean(np.asarray(out[4]) != np.asarray(ref[4])))
    print(f"PARITY: elem mismatch {mism:.4%}, pending mismatch "
          f"{pend_mism:.4%}, max |flux diff| {fdiff:.3e} "
          f"(sum {float(jnp.sum(out[5])):.4f} vs "
          f"{float(jnp.sum(ref[5])):.4f})")

    # -- 3. rate sweep -----------------------------------------------------
    from functools import partial

    # w_tile is pinned by the T(1024) layout law; the meaningful axis
    # is the block size L (the table the one-hot contracts against).
    for divs, ndev in ((6, 2), (8, 2), (8, 1), (12, 8)):
        part, args = chip_workload(divs=divs, ndev=ndev, n=n)
        rows = {}
        for name, fn in (
            ("gather", partial(walk_local, tally=True, tol=1e-6,
                               max_iters=4096)),
            ("vmem", partial(vmem_walk_local, tally=True,
                             tol=1e-6, max_iters=4096,
                             w_tile=1024, interpret=False)),
        ):
            try:
                g = jax.jit(fn)
                r = g(*args)
                jax.block_until_ready(r)
                t0 = time.perf_counter()
                reps = 5
                for _ in range(reps):
                    r = g(*args)
                jax.block_until_ready(r)
                dt = (time.perf_counter() - t0) / reps
                rows[name] = f"{n / dt / 1e6:.2f}M particles/s"
            except Exception as e:  # noqa: BLE001
                rows[name] = f"FAILED {type(e).__name__}: {str(e)[:120]}"
        print(f"L={part.L}: " + "  ".join(f"{k}={v}"
                                          for k, v in rows.items()))

    # -- 4. THE headline experiment: single-chip 48k mesh, blocked vmem
    # sub-split vs the monolithic gather walk (continue protocol) ---------
    from jax.sharding import Mesh as DeviceMesh

    from pumiumtally_tpu import (
        PartitionedPumiTally,
        PumiTally,
        TallyConfig,
    )

    mesh48 = build_box(1, 1, 1, 20, 20, 20, dtype=jnp.float32)  # 48k tets
    nn = min(n, 500_000)
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (nn, 3))
    moves = 4

    def drive(t, seed):
        r = np.random.default_rng(seed)
        t.CopyInitialPosition(src.reshape(-1).copy())
        d = src
        d = np.clip(d + r.normal(scale=0.15, size=d.shape), 0.02, 0.98)
        t.MoveToNextLocation(None, d.reshape(-1).copy())  # warmup/compile
        float(np.asarray(jnp.sum(t.flux)))
        t0 = time.perf_counter()
        for _ in range(moves):
            d = np.clip(d + r.normal(scale=0.15, size=d.shape),
                        0.02, 0.98)
            t.MoveToNextLocation(None, d.reshape(-1).copy())
        total = float(np.asarray(jnp.sum(t.flux)))
        return nn * moves / (time.perf_counter() - t0), total

    try:
        t = PumiTally(mesh48, nn, TallyConfig(
            check_found_all=False, fenced_timing=False))
        rate, total = drive(t, 4)
        print(f"ENGINE mono-gather: {rate / 1e6:.2f}M moves/s "
              f"(sum flux {total:.2f})")
    except Exception as e:  # noqa: BLE001
        print(f"ENGINE mono FAILED: {type(e).__name__}: {str(e)[:200]}")

    dm = DeviceMesh(np.array(jax.devices()[:1]), ("dp",))
    for bound in (512, 1024, 2048, 4096):
        try:
            t = PartitionedPumiTally(
                mesh48, nn,
                TallyConfig(device_mesh=dm, capacity_factor=2.0,
                            walk_vmem_max_elems=bound,
                            check_found_all=False, fenced_timing=False),
            )
            assert t.engine.use_vmem_walk
            rate, total = drive(t, 4)
            print(f"ENGINE vmem bound={bound} "
                  f"(blocks={t.engine.blocks_per_chip}, "
                  f"L={t.engine.part.L}): {rate / 1e6:.2f}M moves/s "
                  f"(rounds={t.engine.last_walk_rounds}, "
                  f"sum flux {total:.2f})")
        except Exception as e:  # noqa: BLE001
            print(f"ENGINE vmem bound={bound} FAILED: "
                  f"{type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 500_000)
