"""Sentinel overhead A/B + audit-cost capture (r9).

Two arms over the IDENTICAL box workload (same mesh, same seeds, same
per-batch protocol: one CopyInitialPosition + ``moves`` continue-mode
moves per source batch):

- ``off``: the default engine (TallyConfig() — no sentinel code runs);
- ``on``:  ``sentinel=SentinelPolicy()`` — per-move on-device audit
  lanes (unfinished count + conservation residual + non-finite probe,
  ONE packed scalar fetch per move) and the straggler ladder armed
  (which must never fire on this healthy workload).

Reported, non-interactively (one JSON line — bench.py's sentinel row
consumes it):

- both arms' moves/s and the relative sentinel overhead (the ≤3%
  budget the round-9 acceptance demands);
- the fenced per-move audit cost (one jitted reduction + one scalar
  D2H) measured on the final state;
- the health report the on-arm accumulated (anomaly_moves must be 0
  and the worst conservation residual within the policy threshold —
  a clean workload that trips its own audit is a sentinel bug);
- the compiles-healthy contract (``compiles.timed == 0``; audit_pack
  compiles once in the warmup batches, never in the timed window).

Flux parity between the arms is asserted BITWISE before any number is
reported — the audit only ever reads engine state, and the ladder
never fires on a healthy run, enforced where the measurement happens.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _make_batches(rng, n: int, batches: int, moves: int):
    src = rng.uniform(0.1, 0.9, (n, 3))
    segs = [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)]
    return [(src, segs) for _ in range(batches)]


def _drive(t, work):
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())


def run_ab(
    n: int = 100_000,
    div: int = 20,
    moves: int = 2,
    batches: int = 8,
) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import (
        PumiTally,
        SentinelPolicy,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(11)
    work = _make_batches(rng, n, batches, moves)

    t_on = PumiTally(
        mesh, n,
        TallyConfig(
            check_found_all=False, fenced_timing=False,
            sentinel=SentinelPolicy(),
        ),
    )
    with retrace_guard(raise_on_exceed=False) as guard:
        _drive(t_on, work[:2])  # warmup: compiles happen here
        jax.block_until_ready(t_on.flux)
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            t0 = time.perf_counter()
            _drive(t_on, work[2:])
            jax.block_until_ready(t_on.flux)
            on_s = time.perf_counter() - t0

    t_off = PumiTally(
        mesh, n, TallyConfig(check_found_all=False, fenced_timing=False)
    )
    _drive(t_off, work[:2])
    jax.block_until_ready(t_off.flux)
    t0 = time.perf_counter()
    _drive(t_off, work[2:])
    jax.block_until_ready(t_off.flux)
    off_s = time.perf_counter() - t0

    # Parity gate: the audit only READS engine state and the ladder
    # never fires on a healthy workload — the on-arm flux must be
    # BITWISE the off-arm flux. RuntimeError (not sys.exit): bench.py
    # wraps this row best-effort.
    if not bool(jnp.all(t_on.flux == t_off.flux)):
        raise RuntimeError(
            "sentinel-on flux diverged bitwise from sentinel-off"
        )

    report = t_on.health_report()
    if report.anomaly_moves != 0 or report.stragglers_lost != 0:
        raise RuntimeError(
            f"sentinel flagged anomalies on a healthy workload: "
            f"{report}"
        )

    # Fenced per-move audit microcost on the final state (one jitted
    # reduction + the packed-scalar fetch).
    runner = t_on._sentinel
    fly = jnp.ones((n,), jnp.int8)
    w = jnp.ones((n,), t_on.dtype)
    done = jnp.ones((n,), bool)
    runner.audit(t_on.x, t_on.x, fly, w, done, t_on.flux)  # warm
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        runner.audit(t_on.x, t_on.x, fly, w, done, t_on.flux)
    audit_ms = (time.perf_counter() - t0) / reps * 1e3
    runner.resync(t_on.flux)

    moves_total = n * moves * (batches - 2)
    return {
        "row": "sentinel",
        "on_moves_per_sec": moves_total / on_s,
        "off_moves_per_sec": moves_total / off_s,
        "sentinel_overhead_pct": (on_s - off_s) / off_s * 100.0,
        "audit_ms": audit_ms,
        "flux_parity_bitwise": True,
        "health": {
            "moves_audited": report.moves_audited,
            "anomaly_moves": report.anomaly_moves,
            "max_conservation_residual":
                report.max_conservation_residual,
            "stragglers_recovered": report.stragglers_recovered,
            "stragglers_lost": report.stragglers_lost,
        },
        # The audit adds exactly ONE entry point (audit_pack), compiled
        # once per particle shape in warmup — never in the timed
        # window; the straggler_retry entry point must not compile at
        # all on a healthy run.
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 8))
    print(json.dumps(run_ab(n=n, div=div, moves=moves, batches=batches),
                     default=float))


if __name__ == "__main__":
    main()
