"""Localization A/B on the current backend: walk-from-centroid vs the
MXU half-space locate (TallyConfig.localization), at bench scale.

Usage: python tools/exp_locate.py [N] [DIV]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
DIV = int(sys.argv[2]) if len(sys.argv) > 2 else 20


def main():
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, DIV, DIV, DIV)
    rng = np.random.default_rng(0)
    srcs = [rng.uniform(0.05, 0.95, (N, 3)) for _ in range(3)]

    for how in ("walk", "locate"):
        t = PumiTally(
            mesh, N,
            TallyConfig(localization=how, check_found_all=False),
        )
        t.CopyInitialPosition(srcs[0].reshape(-1).copy())  # compile
        float(jnp.sum(jnp.asarray(t.elem)))  # sync
        t0 = time.perf_counter()
        for s in srcs[1:]:
            t.CopyInitialPosition(s.reshape(-1).copy())
        float(jnp.sum(jnp.asarray(t.elem)))
        dt = (time.perf_counter() - t0) / (len(srcs) - 1)
        print(f"localization={how}: {dt * 1e3:,.1f} ms per "
              f"{N}-particle CopyInitialPosition "
              f"({N / dt:,.0f} localizations/s)", flush=True)


if __name__ == "__main__":
    main()
