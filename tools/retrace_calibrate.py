#!/usr/bin/env python
"""Diff a ``PUMIUMTALLY_RETRACE_RECORD`` run against RETRACE_BUDGETS.

Recalibrating the retrace tripwire used to be a hand-edit: run the
suite with ``PUMIUMTALLY_RETRACE_RECORD=/tmp/rt.ndjson``, eyeball the
NDJSON, guess new numbers. This makes it one command::

    PUMIUMTALLY_RETRACE_RECORD=/tmp/rt.ndjson \
        JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'
    python tools/retrace_calibrate.py /tmp/rt.ndjson

The record is one JSON object per TEST (written by the tripwire in
tests/conftest.py): ``{"test": nodeid, "total": n, "compiles":
{entry: count}}``. For every entry point this prints the measured
per-test maximum, the declared budget, and the headroom, and flags:

* ``OVER``       — measured max exceeds the budget (the tripwire
  would have failed; the budget needs raising or the retrace fixing);
* ``UNBUDGETED`` — an entry point observed compiling that has no
  budget (the static auditor ``--trace-keys`` reports the same thing
  as JL403 without needing a run);
* ``STALE``      — a budgeted entry the recorded run never compiled
  (informational only: the record may cover a test subset, and
  ``--trace-keys`` JL402 is the authority on truly dead keys).

Exit 1 on OVER or UNBUDGETED, 0 otherwise. The special ``"total"``
budget bounds each test's whole-block compile count and is compared
against the per-test ``total`` field. Pure stdlib — runs without jax,
same stub bootstrap as tools/jaxlint.py.
"""

import argparse
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "pumiumtally_tpu" not in sys.modules:
    _stub = types.ModuleType("pumiumtally_tpu")
    _stub.__path__ = [os.path.join(_REPO, "pumiumtally_tpu")]
    sys.modules["pumiumtally_tpu"] = _stub

from pumiumtally_tpu.analysis.tracekeys import (  # noqa: E402
    EXEMPT_BUDGET_KEYS,
    read_budgets,
)


def load_record(path):
    """(per-entry max compiles, per-test max total, tests read)."""
    max_compiles = {}
    max_total = 0
    ntests = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            ntests += 1
            max_total = max(max_total, int(row.get("total", 0)))
            for entry, count in (row.get("compiles") or {}).items():
                count = int(count)
                if count > max_compiles.get(entry, 0):
                    max_compiles[entry] = count
    return max_compiles, max_total, ntests


def calibrate(budgets, max_compiles, max_total):
    """Rows {entry, budget, measured, headroom, status} sorted by
    entry name, plus the worst status."""
    rows = []
    entries = sorted(set(budgets) | set(max_compiles))
    for entry in entries:
        budget = budgets.get(entry)
        if entry in EXEMPT_BUDGET_KEYS:
            measured = max_total
        else:
            measured = max_compiles.get(entry)
        if budget is None:
            status = "UNBUDGETED"
        elif measured is None:
            status = "STALE"
        elif measured > budget:
            status = "OVER"
        else:
            status = "OK"
        rows.append({
            "entry": entry,
            "budget": budget,
            "measured": measured,
            "headroom": (
                None if budget is None or measured is None
                else budget - measured
            ),
            "status": status,
        })
    failing = any(
        r["status"] in ("OVER", "UNBUDGETED") for r in rows
    )
    return rows, (1 if failing else 0)


def render_text(rows, ntests):
    grid = [["entry point", "budget", "measured", "headroom",
             "status"]]
    for r in rows:
        grid.append([
            r["entry"],
            "—" if r["budget"] is None else str(r["budget"]),
            "—" if r["measured"] is None else str(r["measured"]),
            "—" if r["headroom"] is None else str(r["headroom"]),
            r["status"],
        ])
    widths = [max(len(row[i]) for row in grid)
              for i in range(len(grid[0]))]
    lines = []
    for i, row in enumerate(grid):
        lines.append("  ".join(
            c.ljust(w) for c, w in zip(row, widths)
        ).rstrip())
        if i == 0:
            lines.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    lines.append("")
    lines.append(f"record covers {ntests} test(s)")
    n_over = len([r for r in rows if r["status"] == "OVER"])
    n_unb = len([r for r in rows if r["status"] == "UNBUDGETED"])
    if n_over or n_unb:
        lines.append(
            f"{n_over} over-budget, {n_unb} unbudgeted — edit "
            "config.RETRACE_BUDGETS with a justifying comment"
        )
    else:
        lines.append("every observed entry point within budget")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python tools/retrace_calibrate.py",
        description="diff a PUMIUMTALLY_RETRACE_RECORD NDJSON run "
        "against config.RETRACE_BUDGETS (exit 1 on over-budget or "
        "unbudgeted entries)",
    )
    ap.add_argument(
        "record",
        help="NDJSON file written by PUMIUMTALLY_RETRACE_RECORD",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format",
    )
    args = ap.parse_args(argv)
    if not os.path.exists(args.record):
        print(
            f"retrace_calibrate: no such record: {args.record}",
            file=sys.stderr,
        )
        return 2
    budgets = read_budgets()
    if not budgets:
        print(
            "retrace_calibrate: could not read RETRACE_BUDGETS from "
            "pumiumtally_tpu/config.py",
            file=sys.stderr,
        )
        return 2
    max_compiles, max_total, ntests = load_record(args.record)
    rows, code = calibrate(budgets, max_compiles, max_total)
    if args.format == "json":
        print(json.dumps(
            {"tests": ntests, "rows": rows}, indent=2,
            sort_keys=True,
        ))
    else:
        print(render_text(rows, ntests))
    return code


if __name__ == "__main__":
    sys.exit(main())
