import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from contextlib import ExitStack

import numpy as np

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.utils.chiplock import chip_lock

# Single-client interlock: a verify-drive inside bench's measurement
# window contaminated the round-4 capture (docs/PERF_NOTES.md). Wait
# for the window instead of contending for the chip.
_stack = ExitStack()
if not _stack.enter_context(chip_lock(timeout_s=1800)):
    print("chip lock busy for 30 min; running anyway", file=sys.stderr)

N = 10_000
mesh = build_box(1, 1, 1, 10, 10, 10)
t = PumiTally(mesh, N)
rng = np.random.default_rng(42)
src = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(src.reshape(-1).copy())
assert (t.elem_ids >= 0).all()

# Move 1: some destinations OUTSIDE the box → clamp + partial lengths.
dest = rng.uniform(-0.2, 1.2, (N, 3))
t.MoveToNextLocation(src.reshape(-1).copy(), dest.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
# analytic in-box length per ray (box [0,1]^3), via slab clipping:
d = dest - src
with np.errstate(divide="ignore", invalid="ignore"):
    t_lo = np.where(d != 0, (0.0 - src) / d, -np.inf)
    t_hi = np.where(d != 0, (1.0 - src) / d, np.inf)
tmin = np.minimum(t_lo, t_hi).max(axis=1).clip(0, 1)
tmax = np.maximum(t_lo, t_hi).min(axis=1).clip(0, 1)
expect = np.linalg.norm(d, axis=1) * np.maximum(tmax - tmin, 0)
got = float(np.asarray(t.flux).sum())
rel = abs(got - expect.sum()) / expect.sum()
print(f"conservation: got={got:.4f} expect={expect.sum():.4f} rel={rel:.2e}")
assert rel < 1e-4, "track-length conservation failed"

# clamp check: exited particles sit on a box face
pos = t.positions
out = (dest < 0) | (dest > 1)
exited = out.any(axis=1)
onface = (np.abs(pos) < 1e-4) | (np.abs(pos - 1) < 1e-4)
assert onface[exited].any(axis=1).all(), "exited particles not clamped to face"

# Move 2: dest == origin → zero new flux
f0 = np.asarray(t.flux).copy()
p = t.positions.astype(np.float64)
t.MoveToNextLocation(p.reshape(-1).copy(), p.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
assert np.allclose(np.asarray(t.flux), f0, atol=1e-4), "dest==origin added flux"

# max_iters=2 → warning, no hang
t2 = PumiTally(mesh, 100, TallyConfig(max_iters=2))
s2 = rng.uniform(0.05, 0.95, (100, 3))
t2.CopyInitialPosition(s2.reshape(-1).copy())
print("max_iters=2 probe done (expect warning above)")

# read-only flying → warning not crash
t3 = PumiTally(mesh, 100)
s3 = rng.uniform(0.05, 0.95, (100, 3))
t3.CopyInitialPosition(s3.reshape(-1).copy())
fly_ro = np.ones(100, np.int8); fly_ro.setflags(write=False)
import warnings
with warnings.catch_warnings(record=True) as wlist:
    warnings.simplefilter("always")
    t3.MoveToNextLocation(s3.reshape(-1).copy(), s3.reshape(-1).copy(),
                          fly_ro, np.ones(100))
assert any("read-only" in str(w.message) for w in wlist)
print("read-only flying probe ok")

t.WriteTallyResults("/tmp/fluxresult.vtk")
print("VTK head:", open("/tmp/fluxresult.vtk", "rb").readline().strip())
print("VERIFY DRIVE OK")
