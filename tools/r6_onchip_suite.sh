#!/bin/bash
# Round-6 on-chip suite: fired by a probe loop (tools/r5_probe_loop.sh
# pattern) the moment the TPU tunnel answers. ORDER MATTERS (r4
# lesson): a QUICK headline bench runs first (a short window must
# still yield a fresh cached measurement), then the full bench (which
# now includes the table_precision A/B row in-process), then this
# round's experiment — the two-tier walk-table A/B at full bench
# scale — then the inherited engine experiments; the production-VMEM
# compile+measure goes LAST because its remote compile request remains
# the prime wedge suspect (r4's helper hung rather than erroring).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir (a window
# that closes mid-stage leaves the partial log in place), the digest is
# regenerated before AND after every stage, and the digest write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r6_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r6 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success) for the
# round record. The full bench then overwrites it with the complete
# row set (incl. the table_precision A/B at its reduced shape).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-6 measurement: f32 vs bf16 two-tier walk tables at the
# FULL headline shape (500k particles, 48k tets — the in-bench row
# runs 200k to bound its budget). The select-tier gather is the
# measured bandwidth floor; this is the number that accepts or kills
# the tier (docs/PERF_NOTES.md "Table precision tiers").
run table_ab   1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
# bf16-tier gather sub-split on the bench workload: blocks at 2x L
# (same resident bytes, half the migration-round pressure) — compare
# against bench_clean's f32 gather_blocked row.
run table_ab_blocked 1800 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_REDISTRIBUTION=0 PUMIUMTALLY_WALK_TABLE_DTYPE=bfloat16 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run blocked    3300 python tools/exp_r5_blocked.py 500000 4
# Frontier-local migration (PR 4): the in-loop migrate A/B (full
# capacity vs frontier slab, synthetic + end-to-end) and the blocked
# engine's per-component budget (walk/migrate/occupancy ms per round,
# frontier max/mean) — the "measured component budget + one landed
# optimization" VERDICT r5 item 2 asked for, captured without an
# interactive session.
run frontier_ab     1800 python tools/exp_frontier_ab.py
run blocked_profile 1500 python tools/exp_frontier_ab.py --profile
run native     1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects): the vmem
# kernel sweep, now also asserting the PROJECTED bf16 select-tier
# ceiling (VMEM_FEASIBLE_MAX_ELEMS_BF16) via the AOT path.
run vmem_prod  1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
