"""A/B: argsort-based vs counting-rank redistribution, at bench scale.

The redistribution tax the engine pays per compaction-cascade stage,
per walk_local round, and per migration round used to be a
full-capacity stable argsort (plus, for migration, a permutation
gather); ops/bucketize.py replaces it with counting-rank partitions
that produce the bitwise-identical permutation. This tool measures
both arms on the CURRENT backend at the headline workload's shapes:

1. ``cascade_boundary``  — binary done-key partition of N=500k slots +
   the packed [N,8]f/[N,3]i stage-boundary row gathers (the "packed"
   perm mode's real per-stage cost).
2. ``migrate_round``     — (nparts+1)-bucket keys over the partitioned
   engine's slot capacity + the packed state scatter, both in the old
   sort→gather→scatter form and the new rank→scatter form (the real
   ``_migrate_impl`` cost, nparts=16 like the blocked bench).
3. ``walk_continue``     — end-to-end: one tallied continue-mode
   ``walk()`` over the bench box mesh with
   ``partition_method="rank"`` vs ``"argsort"`` (identical physics,
   pinned bitwise before timing).

Each row prints one JSON line {"row", "argsort_ms"/"rank_ms" or
rates, "speedup"}. Run on CPU now (JAX_PLATFORMS=cpu — the recorded
numbers in docs/PERF_NOTES.md) and re-run unchanged in the next chip
window; honors the chip-window interlock when it runs on hardware.

Usage:
    JAX_PLATFORMS=cpu python tools/exp_partition_ab.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N = int(os.environ.get("PUMIUMTALLY_AB_N", 500_000))
NPARTS = int(os.environ.get("PUMIUMTALLY_AB_NPARTS", 16))
REPS = int(os.environ.get("PUMIUMTALLY_AB_REPS", 5))


def _timed(fn, *args, reps: int = REPS) -> float:
    """Median wall seconds of a jitted fn; forces a value fetch (the
    only real sync on the lazy remote backends — PERF_NOTES r1 §5)."""
    import jax.numpy as jnp

    out = fn(*args)
    float(jnp.sum(out[0] if isinstance(out, tuple) else out))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        float(jnp.sum(out[0] if isinstance(out, tuple) else out))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def bench_cascade_boundary(n: int = N) -> dict:
    """One packed stage boundary: perm of a binary done key + the
    [n,8]f/[n,3]i row gathers ("packed" perm mode)."""
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.ops.bucketize import partition_perm

    rng = np.random.default_rng(3)
    done = jnp.asarray(rng.uniform(size=n) < 0.5)
    fpack = jnp.asarray(rng.random((n, 8), np.float32))
    ipack = jnp.asarray(rng.integers(0, n, (n, 3)), jnp.int32)

    def boundary(method):
        @jax.jit
        def f(done, fpack, ipack):
            perm, _, _ = partition_perm(
                done.astype(jnp.int32), 2, method=method
            )
            return fpack[perm], ipack[perm]

        return f

    t_sort = _timed(boundary("argsort"), done, fpack, ipack)
    t_rank = _timed(boundary("rank"), done, fpack, ipack)
    return {
        "row": "cascade_boundary", "n": n,
        "argsort_ms": t_sort * 1e3, "rank_ms": t_rank * 1e3,
        "speedup": t_sort / t_rank,
    }


def bench_migrate_round(n: int = N, nparts: int = NPARTS) -> dict:
    """One migration shuffle of the packed state matrices: old
    sort→perm-gather→scatter vs new rank→direct-scatter."""
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.ops.bucketize import counting_ranks

    cap_b = int(n // nparts * 1.5)
    cap = nparts * cap_b
    rng = np.random.default_rng(4)
    key = jnp.asarray(rng.integers(0, nparts + 1, cap), jnp.int32)
    fpack = jnp.asarray(rng.random((cap, 11), np.float32))
    ipack = jnp.asarray(rng.integers(0, n, (cap, 8)), jnp.int32)
    fdef = jnp.zeros_like(fpack)
    idef = jnp.zeros_like(ipack)

    @jax.jit
    def old_arm(key, fpack, ipack):
        # The seed's _migrate_impl: argsort, post-sort ranks, then a
        # permutation GATHER feeding the destination scatter.
        perm = jnp.argsort(key, stable=True)
        key_s = key[perm]
        counts = jnp.bincount(key, length=nparts + 1)
        starts = jnp.concatenate(
            [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        pos = jnp.cumsum(jnp.ones_like(key_s)) - 1
        rank = pos - starts[key_s]
        dest = jnp.where(key_s < nparts, key_s * cap_b + rank, cap)
        return (fdef.at[dest].set(fpack[perm], mode="drop"),
                idef.at[dest].set(ipack[perm], mode="drop"))

    def new_arm(method):
        @jax.jit
        def f(key, fpack, ipack):
            rank = counting_ranks(key, nparts + 1, method=method)
            dest = jnp.where(key < nparts, key * cap_b + rank, cap)
            return (fdef.at[dest].set(fpack, mode="drop"),
                    idef.at[dest].set(ipack, mode="drop"))

        return f

    # Parity before timing: identical packed matrices out.
    a = old_arm(key, fpack, ipack)
    b = new_arm("rank")(key, fpack, ipack)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y)), \
            "migrate arms diverged"
    t_old = _timed(old_arm, key, fpack, ipack)
    t_new = _timed(new_arm("rank"), key, fpack, ipack)
    return {
        "row": "migrate_round", "cap": cap, "nparts": nparts,
        "argsort_ms": t_old * 1e3, "rank_ms": t_new * 1e3,
        "speedup": t_old / t_new,
    }


def bench_walk_continue(n: int, div: int = 20, moves: int = 2) -> dict:
    """End-to-end tallied walk, rank vs argsort partitioning (identical
    physics — asserted bitwise before timing)."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from pumiumtally_tpu import TallyConfig, build_box
    from pumiumtally_tpu.api.tally import _localize_step
    from pumiumtally_tpu.ops.walk import walk

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    cfg = TallyConfig()
    tol = cfg.resolved_tolerance(mesh.coords.dtype)
    max_iters = cfg.resolved_max_iters(mesh.nelems)
    rng = np.random.default_rng(5)
    pts = [jnp.asarray(rng.uniform(0.05, 0.95, (n, 3)),
                       mesh.coords.dtype)]
    for _ in range(moves):
        step = rng.normal(scale=0.25 / np.sqrt(3.0), size=(n, 3))
        pts.append(jnp.asarray(
            np.clip(np.asarray(pts[-1], np.float64) + step, 0.02, 0.98),
            mesh.coords.dtype,
        ))
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    x0, e0, ok, _ = _localize_step(
        mesh, jnp.broadcast_to(c0, (n, 3)), jnp.zeros((n,), jnp.int32),
        pts[0], tol=tol, max_iters=max_iters,
    )
    assert bool(jnp.all(ok))
    fly = jnp.ones((n,), jnp.int8)
    w = jnp.ones((n,), mesh.coords.dtype)

    fns = {
        meth: jax.jit(partial(
            walk, tally=True, tol=tol, max_iters=max_iters,
            partition_method=meth,
        ))
        for meth in ("rank", "argsort")
    }

    def run(g):
        flux = jnp.zeros((mesh.nelems,), mesh.coords.dtype)
        x, e = x0, e0
        t0 = time.perf_counter()
        for m in range(1, moves + 1):
            r = g(mesh, x, e, pts[m], fly, w, flux)
            x, e, flux = r.x, r.elem, r.flux
        float(jnp.sum(flux))
        return flux, n * moves / (time.perf_counter() - t0)

    # Warm both arms, then INTERLEAVE timed trials and take each arm's
    # best: back-to-back whole-arm runs otherwise fold CPU
    # frequency/cache ramp into whichever arm runs first (observed as a
    # spurious 7% swing at this scale).
    fluxes, rates = {}, {"rank": [], "argsort": []}
    for meth, g in fns.items():
        fluxes[meth], _ = run(g)
    for _ in range(3):
        for meth, g in fns.items():
            rates[meth].append(run(g)[1])
    assert np.array_equal(
        np.asarray(fluxes["rank"]), np.asarray(fluxes["argsort"])
    ), "walk arms diverged (must be bitwise-identical)"
    rate_r, rate_s = max(rates["rank"]), max(rates["argsort"])
    return {
        "row": "walk_continue", "n": n, "mesh_tets": mesh.nelems,
        "rank_moves_per_sec": rate_r, "argsort_moves_per_sec": rate_s,
        "speedup": rate_r / rate_s, "bitwise_identical": True,
    }


def run_all(n: int = N, nparts: int = NPARTS,
            walk_n: int | None = None) -> list:
    rows = [
        bench_cascade_boundary(n),
        bench_migrate_round(n, nparts),
        bench_walk_continue(walk_n if walk_n is not None else n),
    ]
    return rows


def main() -> None:
    quick = "--quick" in sys.argv
    n = 50_000 if quick else N
    import jax

    from pumiumtally_tpu.utils.chiplock import chip_lock

    on_cpu = jax.default_backend() == "cpu"
    with chip_lock(timeout_s=None, blocking=not on_cpu) as held:
        if not on_cpu and not held:
            print("# chip lock busy; measuring anyway", file=sys.stderr)
        print(f"# backend: {jax.default_backend()}", file=sys.stderr)
        for row in run_all(n, NPARTS, walk_n=n if quick else 200_000):
            print(json.dumps(row))


if __name__ == "__main__":
    main()
