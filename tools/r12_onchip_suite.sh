#!/bin/bash
# r12 on-chip suite (PR 13 — the round-13 pod-scale distributed
# campaign layer; suites are numbered by PR like r8-r11 before it,
# one less than the docs/DESIGN.md round they measure... the r12/PR-12
# batch-fusion round measured itself inside r11's suite, so the
# numbering realigns here).
# Fired by a probe loop (tools/r5_probe_loop.sh pattern) the moment
# the TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK headline
# bench first (a short window must still yield a fresh cached
# measurement), then the full bench (whose row set now includes the
# DISTRIBUTED component row in-process), then THIS round's
# measurement —
#   distributed_ab: collective (all_gather'd counting-rank keys +
#     ppermute ring) vs global-scatter migration at campaign shape,
#     with the BITWISE flux-parity gate and the zero-compile
#     measured-pass contract enforced inside the tool, the modeled
#     per-round migration-collective bytes next to the measured
#     rates, and the 1-proc-vs-2-proc subprocess parity subarm (on a
#     TPU host the CPU subarm exercises gloo if the installed jaxlib
#     carries it; "available": false is an honest report, not a
#     failure). On-chip this decides the round-13 bet: SHIP
#     migrate_collective default-on for pod topologies if the
#     collective arm >= 1.0x scatter on-chip (on CPU it measured
#     ~3.3x — explicit collectives beat GSPMD's resharding of the
#     full-capacity scatter; on TPU the scatter lowers better, so
#     parity is the bar), KILL the default (keep it opt-in) if the
#     ppermute ring costs > 1.2x scatter —
# then the inherited subsystem A/Bs and engine experiments; chipless
# AOT compiles go last (the remote compile helper remains the prime
# wedge suspect).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r12_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r12 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_SCORING=0 PUMIUMTALLY_BENCH_RESILIENCE=0 PUMIUMTALLY_BENCH_SENTINEL=0 PUMIUMTALLY_BENCH_SERVICE=0 PUMIUMTALLY_BENCH_SERVICE_FUSION=0 PUMIUMTALLY_BENCH_DISTRIBUTED=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-13 measurement: collective vs global-scatter migration at
# campaign shape (larger than the in-bench row), plus the 2-process
# parity subarm. Decides the ship/kill rule in the header.
run distributed_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_distributed_ab.py
# The round-12 fusion and round-11 serving-tax re-measures, unchanged
# shapes so rounds compare like-for-like.
run fusion_ab 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=1,4,8,16 PUMIUMTALLY_AB_TRIALS=3 python tools/exp_fusion_ab.py
run service_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_service_ab.py
# Inherited subsystem A/Bs (r7-r10 lineage), unchanged shapes so
# rounds compare like-for-like.
run scoring_ab  1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=6 python tools/exp_scoring_ab.py
run sentinel_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_sentinel_ab.py
run resilience_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_resilience_ab.py
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects).
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
