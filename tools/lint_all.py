#!/usr/bin/env python
"""One-command local lint: exactly what CI's static-analysis workflow
runs, so a green ``python tools/lint_all.py`` predicts green CI.

Runs, in order:

1. ruff  — ``ruff check pumiumtally_tpu/ tests/ bench.py`` (the pinned
   generic Python linter; CI pins ``ruff==X`` and pyproject's ``dev``
   extra carries the same pin). A local ruff whose version drifts from
   that pin is a FAILURE, not a warning: a drifted local can pass
   rules CI fails (or vice versa), which silently un-predicts CI.
   Skipped with a warning when ruff is not installed
   (``pip install -e .[dev]`` provides the pinned version).
2. jaxlint — ``python -m pumiumtally_tpu.analysis pumiumtally_tpu/
   bench.py ...`` (the JAX-aware static analyzer; trace safety JL00x,
   collective safety JL1xx, Pallas kernels JL2xx, host concurrency
   JL3xx, trace-key cardinality JL4xx, determinism JL5xx —
   docs/STATIC_ANALYSIS.md). Always available: pure stdlib.
3. contract audit — ``python -m pumiumtally_tpu.analysis --contracts``
   (the five tally facades vs the shared hook surface; a missing hook
   fails, signature drift is reported but does not).
4. trace-key audit — ``... --trace-keys`` (RETRACE_BUDGETS vs every
   registered jit entry point; a dead budget or unbudgeted entry
   point fails).
5. wire audit — ``... --wire`` (every NDJSON encoder vs the
   AST-extracted SocketFrontend op/reply schema; an unknown op,
   missing field, or reply drift fails).

This is the documented pre-PR check (README). Exit status is non-zero
if ANY stage that ran found issues; a missing ruff does not mask a
jaxlint failure (and vice versa). clang-tidy (the native layer's
linter) is CI-only — it needs a system toolchain this script does not
assume.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUFF_TARGETS = ["pumiumtally_tpu/", "tests/", "bench.py"]
# pumiumtally_tpu/ covers the stats/ (r7), resilience/ (r8),
# sentinel/ (r9), scoring/ (r10) and service/ (r11) subsystems like
# every other package module;
# examples/ and the bench-consumed A/B tools are jax-driving code
# outside the package tree, added explicitly so their trace-safety
# regressions fail the pre-PR check too.
JAXLINT_TARGETS = [
    "pumiumtally_tpu/", "bench.py", "examples/", "tools/exp_stats_ab.py",
    "tools/exp_resilience_ab.py", "tools/exp_sentinel_ab.py",
    "tools/exp_scoring_ab.py", "tools/exp_service_ab.py",
    "tools/exp_fusion_ab.py", "tools/exp_distributed_ab.py",
    "tools/exp_pallas_walk_ab.py", "tools/exp_placement_ab.py",
    "tools/loadgen.py", "tools/exp_service_load.py",
]


def pinned_ruff_version() -> str | None:
    """The ruff pin from pyproject's dev extra (single source of truth
    shared with .github/workflows/static-analysis.yml)."""
    try:
        with open(os.path.join(REPO, "pyproject.toml")) as f:
            m = re.search(r'"ruff==([0-9.]+)"', f.read())
        return m.group(1) if m else None
    except OSError:
        return None


def run_ruff() -> int | None:
    """ruff check; None = ruff unavailable (skipped, with a warning)."""
    ruff = shutil.which("ruff")
    if ruff is None:
        print(
            "lint_all: ruff not installed — SKIPPING the ruff pass "
            "(CI will still run it; `pip install -e .[dev]` installs "
            "the pinned version)",
            file=sys.stderr,
        )
        return None
    pin = pinned_ruff_version()
    local = subprocess.run(
        [ruff, "--version"], capture_output=True, text=True
    ).stdout.strip().split()[-1]
    if pin and local != pin:
        # A drifted ruff makes this script's verdict meaningless as a
        # CI predictor, so drift FAILS — with the one command that
        # fixes it.
        print(
            f"lint_all: FAIL — local ruff {local} != pinned {pin}; "
            f"run `pip install ruff=={pin}` to match CI "
            "(pin lives in pyproject [dev] + static-analysis.yml)",
            file=sys.stderr,
        )
        return 1
    print(f"lint_all: ruff check {' '.join(RUFF_TARGETS)}")
    return subprocess.run([ruff, "check", *RUFF_TARGETS], cwd=REPO).returncode


def run_jaxlint() -> int:
    print(f"lint_all: jaxlint {' '.join(JAXLINT_TARGETS)}")
    # Via tools/jaxlint.py, whose stub-package bootstrap keeps the
    # analyzer importable without jax — same entry CI uses.
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         *JAXLINT_TARGETS],
        cwd=REPO,
    ).returncode


def run_contracts() -> int:
    print("lint_all: jaxlint --contracts (facade hook-surface audit)")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         "--contracts"],
        cwd=REPO,
    ).returncode


def run_trace_keys() -> int:
    print("lint_all: jaxlint --trace-keys (retrace-budget audit)")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         "--trace-keys"],
        cwd=REPO,
    ).returncode


def run_wire() -> int:
    print("lint_all: jaxlint --wire (wire-protocol audit)")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "jaxlint.py"),
         "--wire"],
        cwd=REPO,
    ).returncode


def main() -> int:
    codes = [run_ruff(), run_jaxlint(), run_contracts(),
             run_trace_keys(), run_wire()]
    ran = [c for c in codes if c is not None]
    if any(ran):
        print("lint_all: FAILED", file=sys.stderr)
        return 1
    print("lint_all: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
