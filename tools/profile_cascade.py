"""Stage-level look at the cascade on the bench workload."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from functools import partial
from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.ops.walk import walk

N, DIV, MEAN_STEP = 500_000, 20, 0.25
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
t = PumiTally(mesh, N, TallyConfig(check_found_all=False))
rng = np.random.default_rng(0)
pos = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(pos.reshape(-1).copy())
x, elem = t.x, t.elem
d = jnp.asarray(np.clip(np.asarray(x, np.float64) +
    rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1), x.dtype)
fly = jnp.ones((N,), jnp.int8); w = jnp.ones((N,), x.dtype)
flux = jnp.zeros((mesh.nelems,), x.dtype)

wk = jax.jit(partial(walk, tally=True, tol=1e-6, max_iters=48064))
wk_nc = jax.jit(partial(walk, tally=True, tol=1e-6, max_iters=48064, compact=False))

for tag, f in [("compact", wk), ("plain  ", wk_nc)]:
    r = f(mesh, x, elem, d, fly, w, flux); jax.block_until_ready(r.flux)
    t0 = time.perf_counter()
    for _ in range(3):
        r = f(mesh, x, elem, d, fly, w, flux)
    jax.block_until_ready(r.flux)
    print(f"{tag} walk: {(time.perf_counter()-t0)/3*1e3:7.1f} ms  iters={int(r.iters)}")

# active-count decay: how many particles still active after k iterations?
from pumiumtally_tpu.ops.walk import walk as walk_fn
for k in [1, 2, 4, 8, 16, 32, 64]:
    r = walk_fn(mesh, x, elem, d, fly, w, flux, tally=False, tol=1e-6,
                max_iters=k, compact=False)
    print(f"active after {k:3d} iters: {int(jnp.sum(~r.done))}")
