"""Chipless Mosaic compile harness for the one-kernel Pallas walk.

Compiles ``ops/pallas_walk.py`` AOT against a single-chip v5e topology
using the locally-installed libtpu — NO device, NO tunnel (same
rationale as tools/aot_vmem_compile.py: iterating on Mosaic lowering
through the device tunnel risks wedging the only chip; this path costs
nothing and fails in a killable local process).

One hardening beyond the vmem harness: ``get_topology_desc`` is known
to HANG in some containers (it dials a TPU runtime that is not there),
and a hung certification is worse than a skipped one — stale COMPILE OK
numbers would keep riding in the suite. Every stage here runs under a
SIGALRM deadline; on expiry the harness prints a structured
``SKIP: <stage> timed out`` line and exits 0, so callers (the slow-tier
test, tools/r13_onchip_suite.sh) record the environment gap instead of
wedging or reporting stale numbers.

Usage: python tools/aot_pallas_walk_compile.py [--quick]
           [n] [w_tile] [max_iters] [divs] [blocks]
Prints COMPILE OK <seconds>, SKIP: <reason>, or the compiler error;
exit code 0 for OK/SKIP, 1 for a real compile failure.
"""

from __future__ import annotations

import os
import signal
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The TPU data path is f32; an inherited JAX_ENABLE_X64 (the CPU parity
# suite's env) would promote the workload to f64, which Mosaic rejects.
jax.config.update("jax_enable_x64", False)

TOPOLOGY_DEADLINE_S = int(
    os.environ.get("PUMIUMTALLY_AOT_TOPOLOGY_DEADLINE_S", 120)
)
COMPILE_DEADLINE_S = int(
    os.environ.get("PUMIUMTALLY_AOT_COMPILE_DEADLINE_S", 420)
)


class _StageTimeout(Exception):
    pass


class _deadline:
    """SIGALRM-backed hard deadline for one harness stage (module
    docstring) — a C-level hang in the stage still trips the alarm."""

    def __init__(self, seconds: int, stage: str):
        self.seconds, self.stage = seconds, stage

    def __enter__(self):
        def _fire(signum, frame):
            raise _StageTimeout(self.stage)

        self._prev = signal.signal(signal.SIGALRM, _fire)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._prev)
        return False


def topology_sharding():
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:1x1x1",
        chips_per_host_bounds=[1, 1, 1],
    )
    mesh = topologies.make_mesh(topo, (1,), ("x",))
    return NamedSharding(mesh, P())


def chip_workload(divs: int, ndev: int, n: int, seed: int = 0):
    """A chip's bf16 two-tier slice + particle state, shapes only —
    the AOT path never runs the kernel, it certifies the lowering."""
    import jax.numpy as jnp
    import numpy as np

    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.parallel.partition import build_partition

    mesh = build_box(1, 1, 1, divs, divs, divs, dtype=jnp.float32)
    part = build_partition(mesh, ndev, table_dtype="bfloat16")
    rng = np.random.default_rng(seed)
    chip = 0
    table = part.table[chip * part.L: (chip + 1) * part.L]
    hi = part.table_hi[chip * part.L * 4: (chip + 1) * part.L * 4]
    orig = np.asarray(part.orig_of_glid).reshape(ndev, part.L)[chip]
    owned = np.flatnonzero(orig >= 0)
    lelem = rng.choice(owned, size=n).astype(np.int32)
    coords = np.asarray(mesh.coords)
    tets = np.asarray(mesh.tet2vert)
    cent = coords[tets[orig[lelem]]].mean(axis=1).astype(np.float32)
    dest = (cent + rng.normal(scale=0.2, size=(n, 3))).astype(np.float32)
    return part, (
        jnp.asarray(table), jnp.asarray(hi), jnp.asarray(cent),
        jnp.asarray(lelem), jnp.asarray(dest), jnp.ones(n, jnp.int8),
        jnp.ones(n, jnp.float32), jnp.zeros(n, bool), jnp.zeros(n, bool),
        jnp.zeros(part.L, jnp.float32),
    )


def compile_kernel(n, w_tile, max_iters, divs, ndev=2, blocks=1):
    from functools import partial

    from pumiumtally_tpu.ops.pallas_walk import pallas_walk_local

    with _deadline(TOPOLOGY_DEADLINE_S, "topology acquisition"):
        s = topology_sharding()
    part, args = chip_workload(divs=divs, ndev=ndev, n=n)
    f = partial(pallas_walk_local, tally=True, tol=1e-6,
                max_iters=max_iters, w_tile=w_tile, interpret=False,
                blocks=blocks)
    shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
              for a in args]
    with _deadline(COMPILE_DEADLINE_S, "mosaic+xla compile"):
        t0 = time.perf_counter()
        lowered = jax.jit(f).lower(*shaped)
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        lowered.compile()
    return t_lower, time.perf_counter() - t0, part.L


def main() -> int:
    argv = [a for a in sys.argv[1:] if a != "--quick"]
    quick = "--quick" in sys.argv[1:]
    n = int(argv[0]) if len(argv) > 0 else (2048 if quick else 4096)
    w_tile = int(argv[1]) if len(argv) > 1 else 1024
    max_iters = int(argv[2]) if len(argv) > 2 else 2048
    divs = int(argv[3]) if len(argv) > 3 else (4 if quick else 6)
    blocks = int(argv[4]) if len(argv) > 4 else 1
    try:
        t_lower, t_compile, L = compile_kernel(
            n=n, w_tile=w_tile, max_iters=max_iters, divs=divs,
            blocks=blocks,
        )
    except _StageTimeout as e:
        print(f"SKIP: {e} timed out after its deadline — chipless AOT "
              "unavailable in this container (no reachable TPU compile "
              "runtime); no numbers recorded")
        return 0
    except Exception as e:  # noqa: BLE001 — the harness's question
        print(f"COMPILE FAILED: {type(e).__name__}: {str(e)[:4000]}")
        return 1
    print(f"COMPILE OK: lower {t_lower:.1f}s, mosaic+xla {t_compile:.1f}s "
          f"(L={L}, n={n}, w_tile={w_tile}, max_iters={max_iters}, "
          f"blocks={blocks})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
