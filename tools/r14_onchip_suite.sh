#!/bin/bash
# r14 on-chip suite (PR 18 — the topology-aware placement + collective
# frontier round; suites number by PR-line like r8-r13 before it).
# Fired by a probe loop (tools/r5_probe_loop.sh pattern) the moment
# the TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK headline
# bench first (a short window must still yield a fresh cached
# measurement), then the full bench (whose row set now includes the
# PLACEMENT component row), then THIS round's measurement —
#   placement_ab: linear vs pod_rcb element ownership on the pinned
#     2-host layout (host chips (3,5), tools/exp_placement_ab.py).
#     The tool's gates (equal-host degeneracy bitwise, positions
#     bitwise between arms, boundary-tie-only elem-id diffs, total
#     flux conserved, STRICT modeled cross-host byte drop,
#     compiles.timed == 0) all apply on-chip unchanged. Ship/kill
#     rule (docs/PERF_NOTES.md "Topology-aware placement"): SHIP
#     placement='pod_rcb' as the multi-host default if the pod arm
#     >= 1.15x the linear arm's move rate on a REAL 2-host pod slice
#     (the modeled 33% cross-host byte drop must convert — host hops
#     price ~10x a chip hop there); KILL (keep the knob opt-in) below
#     1.0x, and record the single-host wash honestly — on one host
#     the extra intra-host boundaries are pure cost, so pod_rcb must
#     NEVER become a single-host default.
#   frontier_collective: the composed cap_frontier x
#     migrate_collective engine (the round-19 5-step ring at slab
#     rows) vs the on-chip frontier scatter — bitwise-gated by the
#     tier-1 suite; on-chip the fenced per-move delta decides whether
#     the composed mode becomes the pod-campaign default alongside
#     migrate_collective.
# then the inherited subsystem A/Bs and engine experiments; chipless
# AOT compiles go last (the remote compile helper remains the prime
# wedge suspect).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r14_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r14 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|SKIP|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_SCORING=0 PUMIUMTALLY_BENCH_RESILIENCE=0 PUMIUMTALLY_BENCH_SENTINEL=0 PUMIUMTALLY_BENCH_SERVICE=0 PUMIUMTALLY_BENCH_SERVICE_FUSION=0 PUMIUMTALLY_BENCH_DISTRIBUTED=0 PUMIUMTALLY_BENCH_PALLAS_WALK=0 PUMIUMTALLY_BENCH_PLACEMENT=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-19 measurement: linear vs pod_rcb on the pinned 2-host
# layout at campaign shape. Decides the ship/kill rule in the header.
run placement_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 python tools/exp_placement_ab.py
# The round-13..17 re-measures, unchanged shapes so rounds compare
# like-for-like.
run pallas_walk_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_TRIALS=3 PUMIUMTALLY_AB_BLOCK_ELEMS=8192 python tools/exp_pallas_walk_ab.py
run distributed_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_distributed_ab.py
run fusion_ab 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=1,4,8,16 PUMIUMTALLY_AB_TRIALS=3 python tools/exp_fusion_ab.py
run service_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_service_ab.py
# Inherited subsystem A/Bs (r7-r10 lineage), unchanged shapes so
# rounds compare like-for-like.
run scoring_ab  1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=6 python tools/exp_scoring_ab.py
run sentinel_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_sentinel_ab.py
run resilience_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_resilience_ab.py
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects). The pallas
# harness self-limits with SIGALRM deadlines — SKIP, never a wedge.
run aot_pallas  1200 python tools/aot_pallas_walk_compile.py
run aot_pallas_blocked 1200 python tools/aot_pallas_walk_compile.py 4096 1024 2048 6 2
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
