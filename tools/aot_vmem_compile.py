"""Chipless Mosaic compile harness for the production VMEM walk kernel.

Compiles `ops/vmem_walk.py` AOT against a single-chip v5e topology
using the locally-installed libtpu — NO device, NO tunnel. This exists
because the round-4 remote compile of this kernel hung the device
tunnel's compile helper (tools/r4_onchip/, PERF_NOTES r4): iterating on
Mosaic lowering through the tunnel risks wedging the only chip, while
this path costs nothing and fails (or hangs) in a killable local
process.

The main backend is pinned to CPU (the topology client is
compile-only); `jax.experimental.topologies.get_topology_desc` wants
`chips_per_host_bounds` as a LIST of ints — string forms are rejected.

Usage: python tools/aot_vmem_compile.py [n] [w_tile] [max_iters] [divs] [blocks]
Prints COMPILE OK <seconds> or the full compiler error; exit code 0/1.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The TPU data path is f32; an inherited JAX_ENABLE_X64 (the CPU parity
# suite's env) would promote the workload to f64, which Mosaic rejects.
jax.config.update("jax_enable_x64", False)

from functools import partial  # noqa: E402

from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def topology_sharding():
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:1x1x1",
        chips_per_host_bounds=[1, 1, 1],
    )
    mesh = topologies.make_mesh(topo, (1,), ("x",))
    return NamedSharding(mesh, P())


def compile_kernel(n=4096, w_tile=1024, max_iters=2048, divs=6, ndev=2,
                   blocks=1, tally=True):
    from tools.exp_r4_vmem_compile import chip_workload

    from pumiumtally_tpu.ops.vmem_walk import vmem_walk_local

    s = topology_sharding()
    part, args = chip_workload(divs=divs, ndev=ndev, n=n)
    f = partial(vmem_walk_local, tally=tally, tol=1e-6,
                max_iters=max_iters, w_tile=w_tile, interpret=False,
                blocks=blocks)
    shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
              for a in args]
    t0 = time.perf_counter()
    lowered = jax.jit(f).lower(*shaped)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    lowered.compile()
    return t_lower, time.perf_counter() - t0, part.L


def main() -> int:
    argv = sys.argv[1:]
    n = int(argv[0]) if len(argv) > 0 else 4096
    w_tile = int(argv[1]) if len(argv) > 1 else 1024
    max_iters = int(argv[2]) if len(argv) > 2 else 2048
    divs = int(argv[3]) if len(argv) > 3 else 6
    blocks = int(argv[4]) if len(argv) > 4 else 1
    try:
        t_lower, t_compile, L = compile_kernel(
            n=n, w_tile=w_tile, max_iters=max_iters, divs=divs,
            blocks=blocks,
        )
    except Exception as e:  # noqa: BLE001 — the harness's question
        print(f"COMPILE FAILED: {type(e).__name__}: {str(e)[:4000]}")
        return 1
    print(f"COMPILE OK: lower {t_lower:.1f}s, mosaic+xla {t_compile:.1f}s "
          f"(L={L}, n={n}, w_tile={w_tile}, max_iters={max_iters}, "
          f"blocks={blocks})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
