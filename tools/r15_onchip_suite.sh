#!/bin/bash
# r15 on-chip suite (PR 19 — the streaming chunk-wise fusion +
# heavy-traffic round; suites number by PR-line like r8-r14 before
# it). Fired by a probe loop (tools/r5_probe_loop.sh pattern) the
# moment the TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK
# headline bench first (a short window must still yield a fresh
# cached measurement), then the full bench (whose row set now
# includes the SERVICE_LOAD row and the service_fusion streaming
# sub-row), then THIS round's measurements —
#   service_load: >= 100 scripted clients with a deterministic seeded
#     Poisson schedule through a 2-worker router
#     (tools/exp_service_load.py on top of tools/loadgen.py): served
#     moves/s, client-observed p50/p99 submit->resolve latency,
#     per-lane Jain fairness, refusal counts. The tool's gates
#     (bitwise spot-check parity vs solo replays, compiles.timed == 0
#     via the warmup ladder) apply on-chip unchanged.
#   fusion_ab_stream: the r20 chunk-wise fused STREAMING arm of the
#     fusion A/B at 4/8/16/32 sessions. Ship/kill rule
#     (docs/PERF_NOTES.md "Streaming chunk-wise fusion"): SHIP chunk
#     fusion as the streaming serving default if the fused arm
#     >= 1.15x the unfused arm's served moves/s at 4+ streaming
#     sessions on chip; KILL (gate streaming out of fusion keys
#     again) below 1.0x, and record a wash honestly — the CPU A/B's
#     number rides dispatch overhead that the chip may not share.
# then the inherited subsystem A/Bs and engine experiments; chipless
# AOT compiles go last (the remote compile helper remains the prime
# wedge suspect).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r15_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r15 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|SKIP|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_SCORING=0 PUMIUMTALLY_BENCH_RESILIENCE=0 PUMIUMTALLY_BENCH_SENTINEL=0 PUMIUMTALLY_BENCH_SERVICE=0 PUMIUMTALLY_BENCH_SERVICE_FUSION=0 PUMIUMTALLY_BENCH_SERVICE_LOAD=0 PUMIUMTALLY_BENCH_DISTRIBUTED=0 PUMIUMTALLY_BENCH_PALLAS_WALK=0 PUMIUMTALLY_BENCH_PLACEMENT=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-20 measurements: served throughput under scripted load,
# and the chunk-fused streaming arm whose >= 1.15x gate decides the
# ship/kill rule in the header.
run service_load 1800 env PUMIUMTALLY_AB_N=100000 PUMIUMTALLY_AB_CLIENTS=200 PUMIUMTALLY_AB_RATE=100 PUMIUMTALLY_AB_DIV=12 python tools/exp_service_load.py
run fusion_ab_stream 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=4,8,16,32 PUMIUMTALLY_AB_TRIALS=3 PUMIUMTALLY_AB_FACADE=stream python tools/exp_fusion_ab.py
# The round-14..19 re-measures, unchanged shapes so rounds compare
# like-for-like.
run placement_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 python tools/exp_placement_ab.py
run pallas_walk_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_TRIALS=3 PUMIUMTALLY_AB_BLOCK_ELEMS=8192 python tools/exp_pallas_walk_ab.py
run distributed_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_distributed_ab.py
run fusion_ab 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=1,4,8,16,32 PUMIUMTALLY_AB_TRIALS=3 python tools/exp_fusion_ab.py
run service_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_service_ab.py
# Inherited subsystem A/Bs (r7-r10 lineage), unchanged shapes so
# rounds compare like-for-like.
run scoring_ab  1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=6 python tools/exp_scoring_ab.py
run sentinel_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_sentinel_ab.py
run resilience_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_resilience_ab.py
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects). The pallas
# harness self-limits with SIGALRM deadlines — SKIP, never a wedge.
run aot_pallas  1200 python tools/aot_pallas_walk_compile.py
run aot_pallas_blocked 1200 python tools/aot_pallas_walk_compile.py 4096 1024 2048 6 2
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
