#!/bin/bash
# r13 on-chip suite (PR 17 — the one-kernel Pallas walk round; suites
# number by PR-line like r8-r12 before it).
# Fired by a probe loop (tools/r5_probe_loop.sh pattern) the moment
# the TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK headline
# bench first (a short window must still yield a fresh cached
# measurement), then the full bench (whose row set now includes the
# PALLAS_WALK component row in interpret mode), then THIS round's
# measurement —
#   pallas_walk_ab: the fused select/refine/scatter kernel with
#     grid-pipelined table streaming (walk_kernel='pallas',
#     ops/pallas_walk.py) vs the bf16 gather sub-split at campaign
#     shape, both arms in the blocked regime. On a TPU backend the
#     pallas arm Mosaic-compiles (interpret only on CPU), so THIS
#     stage produces the round-17 decision number; the tool's gates
#     (interpret-mode bitwise pin, bitwise positions between arms,
#     conservation, compiles.timed == 0) all still apply. Ship/kill
#     rule (docs/PERF_NOTES.md "One-kernel walk"): SHIP
#     walk_kernel='pallas' as the blocked bf16 default if the pallas
#     arm >= 1.3x the gather sub-split walk rate on-chip (the 52 B
#     streaming model says the headroom is there), KILL (keep the
#     knob opt-in) below 1.05x.
# then the inherited subsystem A/Bs and engine experiments; chipless
# AOT compiles go last (the remote compile helper remains the prime
# wedge suspect — and the new pallas AOT harness carries its own
# SIGALRM deadlines, so a dead topology client reports SKIP instead
# of wedging the suite).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r13_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r13 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|SKIP|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_SCORING=0 PUMIUMTALLY_BENCH_RESILIENCE=0 PUMIUMTALLY_BENCH_SENTINEL=0 PUMIUMTALLY_BENCH_SERVICE=0 PUMIUMTALLY_BENCH_SERVICE_FUSION=0 PUMIUMTALLY_BENCH_DISTRIBUTED=0 PUMIUMTALLY_BENCH_PALLAS_WALK=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-17 measurement: the one-kernel streamed walk vs the bf16
# gather sub-split at campaign shape, Mosaic-compiled on the chip.
# Decides the ship/kill rule in the header.
run pallas_walk_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_TRIALS=3 PUMIUMTALLY_AB_BLOCK_ELEMS=8192 python tools/exp_pallas_walk_ab.py
# The round-13..16 re-measures, unchanged shapes so rounds compare
# like-for-like.
run distributed_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_DIV=20 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_distributed_ab.py
run fusion_ab 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=1,4,8,16 PUMIUMTALLY_AB_TRIALS=3 python tools/exp_fusion_ab.py
run service_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_service_ab.py
# Inherited subsystem A/Bs (r7-r10 lineage), unchanged shapes so
# rounds compare like-for-like.
run scoring_ab  1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=6 python tools/exp_scoring_ab.py
run sentinel_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_sentinel_ab.py
run resilience_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_resilience_ab.py
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects). The pallas
# harness self-limits with SIGALRM deadlines — SKIP, never a wedge.
run aot_pallas  1200 python tools/aot_pallas_walk_compile.py
run aot_pallas_blocked 1200 python tools/aot_pallas_walk_compile.py 4096 1024 2048 6 2
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
