"""Roofline statement for the walk engine: achieved HBM bytes/s from a
measured move rate, against the chip's streaming peak.

The per-iteration traffic model (all numbers per ACTIVE particle per
crossing, f32):

- walk-table row gather:      80 B read   ([20] floats)
- flux scatter-add:           ~8 B read+write (one f32 slot, amortized)
- carry state read+write:     2 x 37 B    (s4 + elem4 + dest12 + d0_12 +
                                           eff_w4 + done1 — the walk
                                           while_loop carry, ops/walk.py;
                                           idx lives outside the loop and
                                           is part of the cascade costs
                                           below)

plus per-stage cascade costs (argsort key + one concatenate per carried
array) amortized to roughly one extra carry pass over the window, and
the lock-step overdraft: iterations run at the window size, not the
active count — the cascade bounds that waste to ~2x Sigma(path length)
(measured, docs/PERF_NOTES.md round 1).

Usage:
  python tools/roofline.py <moves_per_sec> [crossings_per_move] [hbm_gbps]

Defaults: 15 crossings (bench workload), 820 GB/s (v5e HBM streaming
peak; v5p ~2765).
"""

from __future__ import annotations

import sys

BYTES_GATHER = 80
BYTES_SCATTER = 8
BYTES_CARRY = 2 * 37
CASCADE_FACTOR = 2.0  # lock-step + stage overheads vs ideal Sigma(path)


def roofline(moves_per_sec: float, crossings: float = 15.0,
             hbm_gbps: float = 820.0) -> dict:
    per_crossing = BYTES_GATHER + BYTES_SCATTER + BYTES_CARRY
    bytes_per_move = per_crossing * crossings * CASCADE_FACTOR
    achieved = moves_per_sec * bytes_per_move
    return {
        "bytes_per_move_modeled": bytes_per_move,
        "achieved_GBps": achieved / 1e9,
        "hbm_peak_GBps": hbm_gbps,
        "fraction_of_peak": achieved / (hbm_gbps * 1e9),
        "peak_bound_moves_per_sec": hbm_gbps * 1e9 / bytes_per_move,
    }


def main() -> None:
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    rate = float(sys.argv[1])
    crossings = float(sys.argv[2]) if len(sys.argv) > 2 else 15.0
    hbm = float(sys.argv[3]) if len(sys.argv) > 3 else 820.0
    r = roofline(rate, crossings, hbm)
    print(
        f"{rate:,.0f} moves/s x {r['bytes_per_move_modeled']:,.0f} modeled "
        f"B/move = {r['achieved_GBps']:.1f} GB/s achieved "
        f"= {100 * r['fraction_of_peak']:.1f}% of the {hbm:.0f} GB/s HBM "
        f"streaming peak (bandwidth-bound ceiling at this traffic model: "
        f"{r['peak_bound_moves_per_sec']:,.0f} moves/s)."
    )
    # The binding resource is NOT the streaming peak: the walk-table
    # gather is row-granularity DMA, measured at ~7-10 GB/s effective on
    # v5e (docs/PERF_NOTES.md) — quote that ceiling too.
    for eff in (7.0, 10.0):
        bound = eff * 1e9 / (BYTES_GATHER * crossings * CASCADE_FACTOR)
        print(
            f"  row-gather-bound ceiling at {eff:.0f} GB/s effective DMA: "
            f"{bound:,.0f} moves/s"
        )


if __name__ == "__main__":
    main()
