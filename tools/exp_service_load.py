"""Served-throughput-under-load row: scripted clients vs the router (r20).

bench.py's ``service_load`` row consumes this. It is the heavy-traffic
story measured end to end: ``tools/loadgen.py`` drives >= 100 scripted
OpenMC-style clients — DETERMINISTIC seeded Poisson arrivals, mixed
HIGH/NORMAL/LOW priorities, per-client seeded campaigns — through a
2-worker ``SessionRouter`` (the ``pumiumtally route`` topology), every
client a streaming facade whose moves chunk-fuse with its co-arrivals,
and reports what a capacity planner needs:

- served moves/s and particle-moves/s over the wall clock;
- client-observed p50/p99 submit->resolve latency (the ``wait: true``
  round trip, the number an OpenMC step actually blocks on);
- per-lane served-work Jain fairness;
- refusal counts (per-session busy retries, service-wide admission
  refusals) — the back-pressure the budget converts from OOM risk
  into structured, retryable errors.

Gates enforced HERE, before any number is reported:

- **bitwise spot-check parity**: sampled clients return their flux
  over the wire; each is replayed SOLO on a bare facade from the same
  seeded campaign (``loadgen.client_campaign`` is pure) and must match
  bit for bit — serving under load changes dispatch, never state;
- **compiles.timed == 0**: the measured run dispatches only group
  compositions the warmup ladder pre-compiled (every fused group size
  1..max_fuse at the one (n, chunk) shape all clients share), so no
  compile lands inside the timed window.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _loadgen():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import loadgen

    return loadgen


def _warm_ladder(n: int, div: int, chunk: int, max_fuse: int,
                 moves: int) -> None:
    """Compile every program the measured run can dispatch: for each
    group size K in 1..max_fuse, stage K co-fusable streaming sessions
    against a stopped worker and drain them — K=1 holds the solo
    streaming walk and the chunked localize, K>1 the K-way
    ``walk_fused`` (spans ``(chunk,) * K``, one trace key per K). The
    jit cache keys on shapes and static args, not mesh identity, so a
    ladder-local mesh of the same box spec warms the router workers'
    meshes too."""
    from pumiumtally_tpu import (
        StreamingTally,
        TallyConfig,
        TallyService,
        build_box,
    )

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    cfg = TallyConfig(check_found_all=False, fenced_timing=False)
    rng = np.random.default_rng(2020)
    for k in range(1, max_fuse + 1):
        with TallyService(autostart=False) as svc:
            handles = [
                svc.open_session(
                    StreamingTally(mesh, n, chunk_size=chunk,
                                   config=cfg),
                    session_id=f"warm{k}_{i}", max_queue=moves + 2,
                )
                for i in range(k)
            ]
            futs = []
            for h in handles:
                futs.append(h.copy_initial_position(
                    rng.uniform(0.1, 0.9, n * 3)
                ))
            for _ in range(moves):
                for h in handles:
                    futs.append(h.move(
                        None, rng.uniform(0.1, 0.9, n * 3)
                    ))
            svc.start()
            for f in futs:
                f.result(timeout=600)
            if k == 1:
                # The parity spot-check clients read flux over the
                # wire; hold that program's compile here too.
                handles[0].flux().result(timeout=600)


def run_load_row(
    n: int = 512,
    div: int = 6,
    clients: int = 120,
    rate: float = 300.0,
    moves: int = 2,
    batches: int = 1,
    chunk_divisor: int = 2,
    workers: int = 2,
    max_fuse: int = 8,
    seed: int = 20,
    parity_clients: int = 3,
    timeout: float = 600.0,
) -> dict:
    from pumiumtally_tpu import (
        StreamingTally,
        TallyConfig,
        TallyService,
        build_box,
    )
    from pumiumtally_tpu.service import SessionRouter, SocketFrontend
    from pumiumtally_tpu.utils.profiling import retrace_guard

    lg = _loadgen()
    chunk = max(1, n // chunk_divisor)
    box = (1.0, 1.0, 1.0, div, div, div)
    # Budget ~max_fuse concurrent client batches per worker: generous
    # enough to serve, finite enough that arrival bursts exercise the
    # overloaded-refusal path loadgen retries through.
    budget = max_fuse * n * (moves + 1)
    timed_compiles = 0
    with retrace_guard(raise_on_exceed=False) as guard:
        _warm_ladder(n, div, chunk, max_fuse, moves)
        svcs = [
            TallyService(admission_budget=budget, max_fuse=max_fuse)
            for _ in range(workers)
        ]
        fes = [SocketFrontend(s) for s in svcs]
        for fe in fes:
            fe.start()
        router = SessionRouter([(fe.host, fe.port) for fe in fes])
        router.start()
        try:
            with retrace_guard(raise_on_exceed=False) as tg:
                report = lg.run_load(
                    router.host, router.port, clients=clients,
                    rate=rate, particles=n, batches=batches,
                    moves=moves, facade="stream", chunk_size=chunk,
                    mesh_box=box, seed=seed,
                    collect_flux=parity_clients, timeout=timeout,
                )
            timed_compiles = tg.total_compiles
        finally:
            router.stop()
            for fe in fes:
                fe.stop()
            for s in svcs:
                s.shutdown(drain=False)
    if report["clients_failed"] or report["clients_timed_out"]:
        raise RuntimeError(
            f"load run unhealthy: {report['clients_failed']} failed, "
            f"{report['clients_timed_out']} timed out: "
            f"{report['errors'][:3]}"
        )
    # Bitwise spot-check parity gate: the sampled clients' served flux
    # vs solo replays of their (pure, seeded) campaigns.
    for p in report["parity"]:
        solo = StreamingTally(
            build_box(*box), n, chunk_size=chunk,
            config=TallyConfig(check_found_all=False,
                               fenced_timing=False),
        )
        for src, dests in lg.client_campaign(seed, p["client"], n,
                                             batches, moves):
            solo.CopyInitialPosition(src.copy())
            for d in dests:
                solo.MoveToNextLocation(None, d.copy())
        if not np.array_equal(np.asarray(solo.flux, np.float64),
                              np.asarray(p["flux"], np.float64)):
            raise RuntimeError(
                f"client {p['client']}: served flux diverged bitwise "
                "from the solo replay"
            )
    return {
        "row": "service_load",
        "clients": report["clients"],
        "moves_per_s": report["moves_per_s"],
        "particle_moves_per_s": report["particle_moves_per_s"],
        "latency_ms": report["latency_ms"],
        "lanes": report["lanes"],
        "refusals": report["refusals"],
        "parity_bitwise": True,
        "parity_clients": len(report["parity"]),
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles_per_client": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
            "chunk_size": chunk, "workers": workers,
            "arrival_rate_hz": rate, "admission_budget": budget,
            "seed": seed,
        },
    }


def main() -> None:
    print(json.dumps(run_load_row(
        n=int(os.environ.get("PUMIUMTALLY_AB_N", 512)),
        div=int(os.environ.get("PUMIUMTALLY_AB_DIV", 6)),
        clients=int(os.environ.get("PUMIUMTALLY_AB_CLIENTS", 120)),
        rate=float(os.environ.get("PUMIUMTALLY_AB_RATE", 300.0)),
        moves=int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2)),
        seed=int(os.environ.get("PUMIUMTALLY_AB_SEED", 20)),
    ), default=float))


if __name__ == "__main__":
    main()
