"""Real multi-process jax.distributed smoke: 2 processes x 4 virtual
CPU devices each -> one 8-device global mesh, driven through
initialize_distributed + the sharded tally step.

Each process runs this file with PROC_ID set; process 0 also spawns
process 1 when RUN_BOTH=1. Success criterion: both processes see 8
global devices, the sharded move runs, and the psum'd flux matches the
single-process value.
"""

from __future__ import annotations

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PORT = int(os.environ.get("COORD_PORT", "47123"))


def worker(pid: int) -> None:
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax
    import numpy as np

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel.distributed import (
        UNAVAILABLE_EXIT_CODE,
        DistributedUnavailableError,
        assert_collectives_available,
        init_distributed,
    )

    try:
        mesh_dev = init_distributed(
            coordinator_address=f"127.0.0.1:{PORT}",
            num_processes=2,
            process_id=pid,
        )
    except Exception as e:  # startup failure: classified for the test
        print(f"DISTRIBUTED-INIT-FAILED: {type(e).__name__}: {e}",
              flush=True)
        raise SystemExit(3) from e
    assert mesh_dev.devices.size == 8, mesh_dev
    try:
        # Probe BEFORE the campaign: a CPU jaxlib without gloo cannot
        # execute cross-process collectives at all — exit with the
        # skip marker (the test turns it into a SKIP, not a failure).
        assert_collectives_available(mesh_dev)
    except DistributedUnavailableError as e:
        print(str(e), flush=True)  # carries DISTRIBUTED-UNAVAILABLE
        # No jax.distributed.shutdown(): the barrier would wait on a
        # peer that died of the same error.
        raise SystemExit(UNAVAILABLE_EXIT_CODE) from e
    n = 64
    mesh = build_box(1, 1, 1, 3, 3, 3)
    rng = np.random.default_rng(0)
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = rng.uniform(0.1, 0.9, (n, 3))
    t = PumiTally(mesh, n, TallyConfig(device_mesh=mesh_dev,
                                       check_found_all=False))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    import jax.numpy as jnp

    total = float(jnp.sum(t.flux))
    expect = float(np.linalg.norm(dst - src, axis=1).sum())
    rel = abs(total - expect) / expect
    print(f"proc {pid}: devices={len(jax.devices())} "
          f"flux={total:.6f} rel_err={rel:.2e}", flush=True)
    assert rel < 1e-6

    # Partitioned mode across the SAME two-process mesh: element
    # ownership + particle migration, with the migration collectives
    # crossing the process boundary (the reference's MPI-rank mode,
    # never tested by its own CI).
    from pumiumtally_tpu import PartitionedPumiTally

    pt = PartitionedPumiTally(
        mesh, n,
        TallyConfig(device_mesh=mesh_dev, check_found_all=False,
                    capacity_factor=8.0),
    )
    pt.CopyInitialPosition(src.reshape(-1).copy())
    pt.MoveToNextLocation(None, dst.reshape(-1).copy())
    ptotal = float(jnp.sum(pt.flux))
    prel = abs(ptotal - expect) / expect
    print(f"proc {pid}: partitioned flux={ptotal:.6f} rel_err={prel:.2e}",
          flush=True)
    assert prel < 1e-6
    jax.distributed.shutdown()


def main() -> None:
    pid = int(os.environ.get("PROC_ID", "0"))
    if os.environ.get("RUN_BOTH") == "1" and pid == 0:
        env = dict(os.environ)
        env["PROC_ID"] = "1"
        env.pop("RUN_BOTH")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            worker(0)
        except BaseException:
            # A dead process 0 deadlocks the child's collectives; kill
            # it so the original error surfaces, not a pipe timeout.
            child.kill()
            raise
        finally:
            try:
                out, _ = child.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                child.kill()
                out, _ = child.communicate()
            print("--- child output ---")
            print(out[-2000:])
        if child.returncode != 0:
            raise SystemExit(f"child rc={child.returncode}")
        print("MULTIPROC-OK")
    else:
        worker(pid)


if __name__ == "__main__":
    main()
