"""A/B: linear vs pod-aware RCB placement on a 2-host virtual layout.

PR 12's collective migration made cross-host migration cheap per byte;
round 19's ``TallyConfig.placement="pod_rcb"`` makes it cheap per
PARTICLE by cutting the element tree across hosts FIRST (weighted by
chips per host), then across chips within each host — so the ppermute
ring crosses a host boundary only where the mesh geometry does. This
tool measures both arms on the pinned 2-host layout (host chips (3, 5)
over the 8-device mesh, the 2x1x1 stretched box whose x extent
dominates the RCB axis choice):

1. ``placement_owner`` — construction-level: the equal-host degeneracy
   pin (hosts (4,4) == the linear owner BITWISE) and the modeled
   cross-host migration bytes (ring hops x ``state_pack_columns`` row
   bytes over the remote-face census) for linear vs pod_rcb — the drop
   must be STRICT.
2. ``engine_placement`` — end-to-end: the partitioned engine on the
   bench box workload, linear vs pod_rcb, BOTH arms under the same
   ``placement_hosts`` (hosts describe the machine, not the strategy —
   the linear arm is the topology-blind baseline on the same machine).
   The pinned equivalence class is asserted BEFORE timing: positions
   bitwise equal, every element-id mismatch a boundary tie (adjacent
   elements at the bitwise-identical position — crossing pause points
   land exactly on partition faces; the linear arm shows the same
   attribution class against the monolithic facade), total flux
   conserved, modeled cross-host bytes strictly down. Then fenced
   per-move ms, arms interleaved, and the compiles-healthy contract
   (``compiles.timed == 0``).

CPU rates are the receipt, not the proof: the modeled byte drop IS the
armed bet (host hops are ~10x a chip hop on a real pod), and the CPU —
which prices every block boundary equally — is expected to show a
LOSS: the hierarchical cut trades more intra-host boundaries (host 0's
sub-box is shorter along x, so its internal RCB splits move to y/z and
paths cross more of them — ``walk_rounds`` per arm is in the row) for
strictly fewer host crossings. Record the loss as a loss; the ship
call belongs to the on-chip suite where host hops carry their real
price. Each row prints one JSON line.

Usage:
    JAX_PLATFORMS=cpu python tools/exp_placement_ab.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# The pinned layout needs 8 devices. On the CPU backend, force the
# 8-device virtual mesh BEFORE jax initializes (same idiom as
# tests/conftest.py); a real backend must bring 8 chips of its own.
if os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu"):
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

N = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
MOVES = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 4))
HOSTS = (3, 5)  # the pinned unequal 2-host layout over 8 devices
BOX = (2.0, 1.0, 1.0)
DIV = (16, 8, 8)  # 3/8 of the x layers is a clean cut (6 of 16)


def bench_owner() -> dict:
    """Construction-level row: degeneracy pin + modeled byte drop."""
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.parallel.distributed import (
        modeled_cross_host_migration_bytes,
    )
    from pumiumtally_tpu.parallel.partition import build_partition

    fcols, icols = 10, 9  # the 13-lane engine state layout
    mesh = build_box(*BOX, *DIV)
    p_lin = build_partition(mesh, 8)
    p_eq = build_partition(mesh, 8, placement="pod_rcb", hosts=[4, 4])
    assert np.array_equal(p_lin.owner, p_eq.owner), (
        "equal-host pod_rcb must reproduce the linear owner bitwise"
    )
    p_pod = build_partition(mesh, 8, placement="pod_rcb",
                            hosts=list(HOSTS))
    b_lin = modeled_cross_host_migration_bytes(
        p_lin.remote_faces, 1, HOSTS, fcols, icols)
    b_pod = modeled_cross_host_migration_bytes(
        p_pod.remote_faces, 1, HOSTS, fcols, icols)
    assert b_pod < b_lin, (b_lin, b_pod)
    return {
        "row": "placement_owner", "mesh_tets": mesh.nelems,
        "hosts": list(HOSTS), "equal_host_degeneracy_bitwise": True,
        "bytes_linear": b_lin, "bytes_pod_rcb": b_pod,
        "drop_frac": (b_lin - b_pod) / b_lin,
    }


def _fenced_move_ms(t, pts, first: int, last: int) -> list:
    """Per-move wall ms, each move fenced by a scalar flux fetch (the
    only real sync on the lazy backends — PERF_NOTES r1 §5)."""
    import jax.numpy as jnp

    out = []
    for m in range(first, last + 1):
        t0 = time.perf_counter()
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())
        float(jnp.sum(t.flux))
        out.append((time.perf_counter() - t0) * 1e3)
    return out


def bench_engine(n: int = N, moves: int = MOVES) -> dict:
    """End-to-end row: the pinned equivalence class, then fenced
    per-move ms for both arms, interleaved."""
    import jax.numpy as jnp

    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel import make_device_mesh
    from pumiumtally_tpu.utils.profiling import retrace_guard

    import bench  # the canonical workload generator — one convention

    mesh = build_box(*BOX, *DIV)
    rng = np.random.default_rng(0)
    pts = bench.make_trajectory(rng, n, 2 * moves + 2, box=list(BOX))
    dm = make_device_mesh(8)

    def build(placement):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(device_mesh=dm, placement=placement,
                        placement_hosts=HOSTS, check_found_all=False,
                        fenced_timing=False),
        )
        t.CopyInitialPosition(pts[0].reshape(-1).copy())
        # TWO warmup moves: move 1 compiles the staged-source phase,
        # move 2 the continue-protocol phase the timed window drives —
        # both programs land before timing (compiles.timed == 0).
        for m in (1, 2):
            t.MoveToNextLocation(None, pts[m].reshape(-1).copy())
            float(jnp.sum(t.flux))
        return t

    with retrace_guard(raise_on_exceed=False) as guard:
        t_lin = build("linear")
        t_pod = build("pod_rcb")
        # The class gate runs BEFORE timing: a placement that changes
        # physics must never get a rate reported.
        b_lin = t_lin.engine.modeled_cross_host_bytes()
        b_pod = t_pod.engine.modeled_cross_host_bytes()
        assert 0 < b_pod < b_lin, (b_lin, b_pod)
        np.testing.assert_array_equal(t_lin.positions, t_pod.positions)
        el = np.asarray(t_lin.elem_ids)
        ep = np.asarray(t_pod.elem_ids)
        adj = np.asarray(mesh.face_adj)
        ties = np.nonzero(el != ep)[0]
        for i in ties:
            assert el[i] in adj[ep[i]] or ep[i] in adj[el[i]], (
                f"particle {i}: element {el[i]} vs {ep[i]} is not a "
                "boundary tie"
            )
        f_lin = np.asarray(t_lin.flux, np.float64)
        f_pod = np.asarray(t_pod.flux, np.float64)
        rtol = (1e-12 if np.asarray(t_lin.flux).dtype == np.float64
                else 1e-6)
        np.testing.assert_allclose(f_lin.sum(), f_pod.sum(), rtol=rtol)
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            # Interleaved fenced windows: arm A then arm B on the same
            # trajectory slice, twice (the exp_partition_ab ramp
            # lesson — ambient drift hits both arms equally).
            ms = {"linear": [], "pod_rcb": []}
            for half in range(2):
                lo = 3 + half * moves
                hi = lo + moves - 1
                ms["linear"] += _fenced_move_ms(t_lin, pts, lo, hi)
                ms["pod_rcb"] += _fenced_move_ms(t_pod, pts, lo, hi)
    ms_lin = float(np.median(ms["linear"]))
    ms_pod = float(np.median(ms["pod_rcb"]))
    return {
        "row": "engine_placement", "n": n, "mesh_tets": mesh.nelems,
        "hosts": list(HOSTS),
        "bytes_linear": b_lin, "bytes_pod_rcb": b_pod,
        "drop_frac": (b_lin - b_pod) / b_lin,
        "positions_bitwise": True, "boundary_ties": int(len(ties)),
        "total_flux_rel_err": float(
            abs(f_lin.sum() - f_pod.sum()) / f_lin.sum()
        ),
        "linear_move_ms": ms_lin, "pod_rcb_move_ms": ms_pod,
        "linear_moves_per_sec": n / (ms_lin / 1e3),
        "pod_rcb_moves_per_sec": n / (ms_pod / 1e3),
        "speedup": ms_lin / ms_pod,
        # More intra-host boundaries is the price of fewer host
        # crossings: the per-arm round count makes it visible.
        "linear_walk_rounds": t_lin.engine.last_walk_rounds,
        "pod_rcb_walk_rounds": t_pod.engine.last_walk_rounds,
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
    }


def run_ab(n: int = N, moves: int = MOVES) -> dict:
    """The bench.py component row: both rows keyed by name."""
    return {
        r.pop("row"): r for r in (bench_owner(), bench_engine(n, moves))
    }


def main() -> None:
    import jax

    from pumiumtally_tpu.utils.chiplock import chip_lock

    quick = "--quick" in sys.argv
    n = 20_000 if quick else N
    on_cpu = jax.default_backend() == "cpu"
    with chip_lock(timeout_s=None, blocking=not on_cpu) as held:
        if not on_cpu and not held:
            print("# chip lock busy; measuring anyway", file=sys.stderr)
        print(f"# backend: {jax.default_backend()}", file=sys.stderr)
        print(json.dumps(bench_owner()))
        print(json.dumps(bench_engine(n, MOVES)))


if __name__ == "__main__":
    main()
