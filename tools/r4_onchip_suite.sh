#!/bin/bash
# Round-4 on-chip suite: fires once when the TPU tunnel recovers.
#
# CHECKED-IN COPY of the armed recovery suite (live instance:
# /tmp/r3_onchip_suite.sh, fired once by /tmp/r3_probe_loop.sh when
# the TPU tunnel answers). Kept in-repo so the round records what was
# armed even if the tunnel never clears.
# Writes logs to /tmp/r3_onchip/ and mirrors them into the repo
# (tools/r4_onchip/) so a late recovery still leaves evidence on disk.
set -u
OUT=/tmp/r3_onchip
mkdir -p "$OUT"
cd /root/repo
echo "suite started $(date)" > "$OUT/status"
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$OUT/status"
  # Mirror incrementally: a round ending mid-suite must still find the
  # finished steps' evidence in the repo.
  mkdir -p /root/repo/tools/r4_onchip
  cp "$OUT/$name.log" "$OUT/status" /root/repo/tools/r4_onchip/ 2>/dev/null
}
# Value-ordered: if the tunnel re-wedges mid-suite, the logs already
# written answer the biggest open questions first (Mosaic lowering of
# the production vmem kernel + the bound sweep, then the cascade knob
# sweep, then the protocol A/B, then a full bench record).
run vmem_prod 1800 python tools/exp_r4_vmem_compile.py 500000
run cascade   1800 python tools/exp_r3_cascade.py 500000 20 4
run api_ab    900 python tools/exp_r2_api.py 500000 20 6
run bench     2700 python bench.py
run scale     1800 python tools/exp_r4_scale.py 500000
run vmem      1800 python tools/exp_r3_vmem.py bench 500000
run locate_ab 900 python tools/exp_locate.py 500000 20
run profile   900 python tools/exp_r2_profile.py
run native    1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
echo "suite finished $(date)" >> "$OUT/status"
timeout 120 python tools/analyze_r3_onchip.py "$OUT" > "$OUT/digest.md" 2>&1
mkdir -p /root/repo/tools/r4_onchip && cp "$OUT"/*.log "$OUT/digest.md" "$OUT/status" /root/repo/tools/r4_onchip/ 2>/dev/null
