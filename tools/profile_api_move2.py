"""Where does the API move spend time now?"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from pumiumtally_tpu import PumiTally, TallyConfig, build_box

N, DIV, MEAN_STEP = 500_000, 20, 0.25
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
t = PumiTally(mesh, N, TallyConfig(check_found_all=False))
rng = np.random.default_rng(0)
pos = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(pos.reshape(-1).copy())
d0 = np.clip(pos + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)
t.MoveToNextLocation(pos.reshape(-1).copy(), d0.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
pos = t.positions.astype(np.float64)
for _ in range(3):
    d = np.clip(pos + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)
    t0 = time.perf_counter()
    t.MoveToNextLocation(pos.reshape(-1).copy(), d.reshape(-1).copy(),
                         np.ones(N, np.int8), np.ones(N))
    t1 = time.perf_counter()
    pos = t.positions.astype(np.float64)
    t2 = time.perf_counter()
    print(f"move: {1e3*(t1-t0):6.1f} ms | positions readback: {1e3*(t2-t1):6.1f} ms")
