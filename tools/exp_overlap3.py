"""Overlap: upload fresh 6MB while the real walk computes (~450ms)."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.api.tally import _move_step

N, DIV, MEAN_STEP = 500_000, 20, 0.25
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
t = PumiTally(mesh, N, TallyConfig(check_found_all=False))
rng = np.random.default_rng(0)
pos = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(pos.reshape(-1).copy())
d0 = np.clip(pos + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)
t.MoveToNextLocation(pos.reshape(-1).copy(), d0.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
x, elem, flux = t.x, t.elem, t.flux
fly = jnp.ones((N,), jnp.int8); w = jnp.ones((N,), x.dtype)
fresh = [rng.uniform(0.05, 0.95, (N, 3)).astype(np.float32) for _ in range(6)]

def run_move(x, elem, flux, dest_dev):
    return _move_step(mesh, x, elem, x, dest_dev, fly, w, flux,
                      tol=t._tol, max_iters=t._max_iters)

d_dev = jax.device_put(fresh[0])
x, elem, flux, _ = run_move(x, elem, flux, d_dev); jax.block_until_ready(flux)

# serial: upload then compute
t0 = time.perf_counter()
d_dev = jax.device_put(fresh[1]); jax.block_until_ready(d_dev)
x, elem, flux, _ = run_move(x, elem, flux, d_dev); jax.block_until_ready(flux)
t_serial = time.perf_counter() - t0

# pipelined: dispatch compute with PREVIOUSLY staged dest, upload next during it
d_next = jax.device_put(fresh[2]); jax.block_until_ready(d_next)
t0 = time.perf_counter()
for i in (3, 4, 5):
    x, elem, flux, _ = run_move(x, elem, flux, d_next)  # async dispatch
    d_next = jax.device_put(fresh[i])                   # upload while computing
jax.block_until_ready((flux, d_next))
t_pipe = (time.perf_counter() - t0) / 3
print(f"serial={t_serial*1e3:.0f}ms  pipelined-per-move={t_pipe*1e3:.0f}ms")

# force a REAL sync by fetching one scalar
t0 = time.perf_counter()
x, elem, flux, _ = run_move(x, elem, flux, d_next)
s = float(jnp.sum(flux))
t_real = time.perf_counter() - t0
print(f"move + scalar fetch = {t_real*1e3:.0f}ms (sum={s:.1f})")
t0 = time.perf_counter()
x, elem, flux, _ = run_move(x, elem, flux, d_next)
jax.block_until_ready(flux)
t_b = time.perf_counter() - t0
t0 = time.perf_counter()
s = float(jnp.sum(flux))
t_f = time.perf_counter() - t0
print(f"move+block={t_b*1e3:.0f}ms then fetch={t_f*1e3:.0f}ms")
