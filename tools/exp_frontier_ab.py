"""A/B: frontier-slab vs full-capacity in-loop migration, at bench scale.

The blocked engine's phase loop used to pay a FULL-CAPACITY migrate
round (an (nparts+1)-bucket counting rank over every slot plus two
packed full-capacity scatters) every walk/migrate round — 45 rounds on
the 1M-tet lattice smoke run — even when the crossing front was a
handful of particles. parallel/partition.py's frontier slab
(`TallyConfig.cap_frontier`) moves only the pending rows; this tool
measures both arms on the CURRENT backend:

1. ``migrate_round_frontier`` — one synthetic in-loop migration round
   at the headline capacity (nparts=16 like the blocked bench), swept
   over frontier fractions: full ``_migrate_impl`` ms vs
   ``_frontier_migrate_impl`` ms. Slab-size invariance is asserted
   bitwise before timing (slab=cap_frontier vs slab=cap produce the
   identical state — the same-destinations contract).
2. ``engine_frontier`` — end-to-end: the gather-blocked engine on the
   bench box workload with cap_frontier OFF vs ON (slab self-sized to
   the measured ``last_frontier_max``, so no round falls back), rates
   interleaved. Per-particle observables are asserted bitwise equal
   between the arms; flux agreement is scatter-order-only
   (docs/DESIGN.md frontier invariant).

``--profile`` instead emits the blocked component-budget row
(bench.run_blocked_profile) — per-round walk/migrate/occupancy ms,
rounds, dispatches, frontier max/mean — for the r6 chip window.

Each row prints one JSON line. The honest contract from PR 1 applies:
record a wash as a wash — the thesis is that the CHIP pays the
full-capacity rank+scatter per block per round, CPU numbers are the
armed bet's receipt, not its proof.

Usage:
    JAX_PLATFORMS=cpu python tools/exp_frontier_ab.py [--quick|--profile]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N = int(os.environ.get("PUMIUMTALLY_AB_N", 500_000))
NPARTS = int(os.environ.get("PUMIUMTALLY_AB_NPARTS", 16))
REPS = int(os.environ.get("PUMIUMTALLY_AB_REPS", 5))


def _timed(fn, *args, reps: int = REPS) -> float:
    """Median wall seconds of a jitted fn; forces a value fetch (the
    only real sync on the lazy remote backends — PERF_NOTES r1 §5)."""
    import jax.numpy as jnp

    def once():
        out = fn(*args)
        leaf = out[0] if isinstance(out, tuple) else out
        if isinstance(leaf, dict):
            leaf = leaf["x"]
        float(jnp.sum(leaf))

    once()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        once()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _synthetic_state(cap: int, nparts: int, part_L: int, frac: float,
                     seed: int = 7) -> dict:
    """An in-loop-shaped state: ~2/3 of the slots alive (the engine's
    1.5x capacity_factor headroom — without slack, random migration
    targets overflow some part almost surely), a ``frac`` fraction of
    them paused at a partition face (pending = a random remote glid)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    alive = rng.uniform(size=cap) < 1 / 1.5
    pend = np.full(cap, -1, np.int32)
    movers = alive & (rng.uniform(size=cap) < frac)
    pend[movers] = rng.integers(0, nparts * part_L, movers.sum())
    return {
        "x": jnp.asarray(rng.random((cap, 3))),
        "dest": jnp.asarray(rng.random((cap, 3))),
        "w": jnp.asarray(rng.random(cap)),
        "lelem": jnp.asarray(rng.integers(0, part_L, cap), jnp.int32),
        "pending": jnp.asarray(pend),
        "pid": jnp.asarray(
            np.where(alive, np.arange(cap), -1), jnp.int32
        ),
        "alive": jnp.asarray(alive),
        "done": jnp.asarray(~movers),
        "exited": jnp.zeros((cap,), bool),
        "lost": jnp.zeros((cap,), bool),
        "fly": jnp.asarray(alive.astype(np.int8)),
    }


def bench_migrate_round(n: int = N, nparts: int = NPARTS,
                        frac: float = 0.02) -> dict:
    """One in-loop migration round, full-capacity vs frontier slab."""
    import jax

    from pumiumtally_tpu.parallel.partition import (
        _frontier_migrate_impl,
        _migrate_impl,
    )

    part_L = 4096
    cap_b = int(n // nparts * 1.5)
    cap = nparts * cap_b
    state = _synthetic_state(cap, nparts, part_L, frac)
    n_move = int(np.asarray(state["pending"] >= 0).sum())
    # Static slab: the smallest power of two holding this front (what
    # a deployment would configure from last_frontier_max).
    cap_frontier = 1 << max(1, (n_move - 1)).bit_length()

    @jax.jit
    def full(st):
        return _migrate_impl(part_L, nparts, cap_b, st)

    def frontier(k):
        @jax.jit
        def f(st):
            return _frontier_migrate_impl(part_L, nparts, cap_b, k, st)

        return f

    # Slab-size invariance (the same-destinations contract): the
    # working slab and the full-capacity slab must produce the
    # bitwise-identical state.
    a = frontier(cap_frontier)(state)
    b = frontier(cap)(state)
    assert not bool(a[1]) and not bool(b[1]), "unexpected overflow"
    for k in state:
        assert np.array_equal(np.asarray(a[0][k]), np.asarray(b[0][k])), (
            f"frontier slab-size divergence in {k!r}"
        )
    t_full = _timed(full, state)
    t_frontier = _timed(frontier(cap_frontier), state)
    return {
        "row": "migrate_round_frontier", "cap": cap, "nparts": nparts,
        "frontier": n_move, "frontier_frac": n_move / cap,
        "cap_frontier": cap_frontier,
        "full_ms": t_full * 1e3, "frontier_ms": t_frontier * 1e3,
        "speedup": t_full / t_frontier,
        "slab_invariance_bitwise": True,
    }


def bench_engine(n: int, div: int = 20, moves: int = 4) -> dict:
    """End-to-end gather-blocked engine, cap_frontier off vs on."""
    import jax.numpy as jnp

    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box

    import bench  # the canonical workload generator — one convention

    bound = int(os.environ.get("PUMIUMTALLY_BENCH_BLOCK_ELEMS", 3072))
    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(0)
    pts = bench.make_trajectory(rng, n, moves + 1)

    def build(cap_frontier):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(capacity_factor=2.0, walk_vmem_max_elems=bound,
                        walk_block_kernel="gather",
                        cap_frontier=cap_frontier,
                        check_found_all=False, fenced_timing=False),
        )
        t.CopyInitialPosition(pts[0].reshape(-1).copy())
        t.MoveToNextLocation(None, pts[1].reshape(-1).copy())  # warmup
        float(jnp.sum(t.flux))
        return t

    t_off = build(None)
    # Self-size the slab from the measured front: no fallback rounds,
    # the pure frontier arm. Recorded in the row.
    front_max = t_off.engine.last_frontier_max
    cap_frontier = 1 << max(1, (max(front_max, 1) * 2 - 1)).bit_length()
    t_on = build(cap_frontier)

    def run(t):
        t0 = time.perf_counter()
        for m in range(2, moves + 2):
            t.MoveToNextLocation(None, pts[m].reshape(-1).copy())
        float(jnp.sum(t.flux))
        return n * moves / (time.perf_counter() - t0)

    # Interleaved trials, best-of (the exp_partition_ab ramp lesson).
    rates = {"off": [], "on": []}
    for _ in range(3):
        rates["off"].append(run(t_off))
        rates["on"].append(run(t_on))
    # Per-particle observables must agree bitwise between the arms;
    # flux agreement is scatter-order-only (different but equally
    # valid slot layouts — docs/DESIGN.md), so the tolerance is a few
    # ulps of the WORKING dtype (this tool runs f32 by default).
    np.testing.assert_array_equal(t_on.positions, t_off.positions)
    np.testing.assert_array_equal(t_on.elem_ids, t_off.elem_ids)
    f_on = np.asarray(t_on.flux, np.float64)
    f_off = np.asarray(t_off.flux, np.float64)
    rtol = 1e-12 if np.asarray(t_on.flux).dtype == np.float64 else 2e-6
    np.testing.assert_allclose(f_on, f_off, rtol=rtol, atol=rtol)
    r_off, r_on = max(rates["off"]), max(rates["on"])
    return {
        "row": "engine_frontier", "n": n, "mesh_tets": mesh.nelems,
        "blocks": t_off.engine.nparts, "cap": t_off.engine.cap,
        "cap_frontier": cap_frontier,
        "frontier_max": t_on.engine.last_frontier_max,
        "frontier_mean": t_on.engine.last_frontier_mean,
        "fallback_rounds": t_on.engine.last_fallback_rounds,
        "walk_rounds_last_move": t_on.engine.last_walk_rounds,
        "off_moves_per_sec": r_off, "on_moves_per_sec": r_on,
        "speedup": r_on / r_off,
        "positions_elems_bitwise": True,
    }


def run_all(n: int = N, nparts: int = NPARTS,
            engine_n: int | None = None) -> list:
    return [
        bench_migrate_round(n, nparts, frac=0.02),
        bench_migrate_round(n, nparts, frac=0.20),
        bench_engine(engine_n if engine_n is not None else min(n, 200_000)),
    ]


def main() -> None:
    import jax

    from pumiumtally_tpu.utils.chiplock import chip_lock

    quick = "--quick" in sys.argv
    profile = "--profile" in sys.argv
    n = 50_000 if quick else N
    on_cpu = jax.default_backend() == "cpu"
    with chip_lock(timeout_s=None, blocking=not on_cpu) as held:
        if not on_cpu and not held:
            print("# chip lock busy; measuring anyway", file=sys.stderr)
        print(f"# backend: {jax.default_backend()}", file=sys.stderr)
        if profile:
            import bench

            row = bench.run_blocked_profile(min(n, 200_000), 3)
            row["row"] = "blocked_profile"
            print(json.dumps(row))
            return
        for row in run_all(n, NPARTS, engine_n=n if quick else None):
            print(json.dumps(row))


if __name__ == "__main__":
    main()
