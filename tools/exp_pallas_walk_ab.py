"""Pallas one-kernel walk A/B: bf16 gather sub-split vs the fused
streaming kernel (round 17 — bench.py's "pallas_walk" row consumes the
JSON line).

Two layers over the IDENTICAL seeded partitioned workload:

- Parity first (the gate numbers are meaningless without): a
  kernel-level INTERPRET-mode pin — ``pallas_walk_local`` run with
  ``interpret=True`` against ``walk_local``'s two-tier path on a mixed
  pause/exit/hold workload, positions/elements/pending BITWISE, flux in
  the documented reassociation class. Interpret mode executes the exact
  kernel arithmetic on CPU, so this gate is backend-independent and
  runs before any rate is reported (sys.exit(1) on violation).

- Rates: both ENGINES (``walk_kernel='gather'`` vs ``'pallas'``, both
  on the bf16 two-tier tables, both forced into the blocked regime by
  ``walk_vmem_max_elems`` so the pallas arm actually STREAMS) at bench
  shape, timed passes INTERLEAVED between arms (PERF_NOTES r5
  measurement note), median per arm, plus FENCED per-move ms and the
  compiles-healthy contract — ``compiles.timed == 0``: the pallas round
  program is one phase-program variant, compiled in warmup, never in a
  measured window. Cross-arm flux agreement and the conservation gate
  are enforced on the timed arms too.

- Bytes provenance: ``modeled_walk_bytes`` — the 80 B/crossing f32
  gather model vs the 52 B two-tier model both arms share (the pallas
  arm approaches it as sequential block DMA instead of random row
  gathers; the A/B exists to measure whether that matters on chip).

On CPU the pallas arm runs in pallas INTERPRET mode — a correctness
vehicle, not a rate (expect a large slowdown; the recorded CPU
"speedup" is NOT the ship/kill number). The ship/kill rule for the
on-chip decision lives in docs/PERF_NOTES.md "One-kernel walk": SHIP
at >= 1.3x blocked-walk rate on chip, KILL below 1.05x.

Usage:
    JAX_PLATFORMS=cpu python tools/exp_pallas_walk_ab.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N = int(os.environ.get("PUMIUMTALLY_AB_N", 16_384))
DIV = int(os.environ.get("PUMIUMTALLY_AB_DIV", 8))  # 8^3 cells = 3072 tets
MOVES = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
TRIALS = int(os.environ.get("PUMIUMTALLY_AB_TRIALS", 2))
BLOCK_ELEMS = int(os.environ.get("PUMIUMTALLY_AB_BLOCK_ELEMS", 1024))
CONSERVATION_RTOL = 1e-6
# Flux between the arms differs only in accumulation order (per-tile
# matmul partials vs cascaded scatter-adds): a few f32 ulps per bin,
# compounding to ~1e-6 of the peak bin over a multi-pass campaign.
# 5e-6 holds that class with margin while still catching any real
# corruption (a wrong crossing shifts whole track segments, 1e-2+).
CROSS_ARM_RTOL = 5e-6


def _interpret_parity_gate() -> dict:
    """The kernel-level interpret-mode pin (module docstring). Returns
    the gate's evidence record; raises SystemExit on violation."""
    import jax.numpy as jnp

    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.ops.pallas_walk import pallas_walk_local
    from pumiumtally_tpu.parallel.partition import (
        build_partition,
        walk_local,
    )

    # The mixed pause/exit/hold/dead workload the parity pin needs —
    # mirrors tests/test_pallas_walk.py's _chip_workload.
    mesh = build_box(1, 1, 1, 4, 4, 4)
    part = build_partition(mesh, 4, table_dtype="bfloat16")
    rng = np.random.default_rng(17)
    chip, n = 1, 1500
    table = part.table[chip * part.L: (chip + 1) * part.L]
    hi = part.table_hi[chip * part.L * 4: (chip + 1) * part.L * 4]
    orig = np.asarray(part.orig_of_glid).reshape(4, part.L)[chip]
    owned = np.flatnonzero(orig >= 0)
    lelem = rng.choice(owned, size=n).astype(np.int32)
    coords = np.asarray(mesh.coords)
    tets = np.asarray(mesh.tet2vert)
    cent = coords[tets[orig[lelem]]].mean(axis=1)
    fly = (rng.random(n) > 0.15).astype(np.int8)
    dest = np.where(fly[:, None] == 1,
                    cent + rng.normal(scale=0.25, size=(n, 3)), cent)
    args = (jnp.asarray(cent), jnp.asarray(lelem), jnp.asarray(dest),
            jnp.asarray(fly), jnp.asarray(rng.uniform(0.5, 2.0, n)),
            jnp.asarray(rng.random(n) < 0.1), jnp.zeros(n, bool),
            jnp.zeros((part.L,), jnp.float32))
    kw = dict(tally=True, tol=1e-8, max_iters=4096)
    ref = walk_local(table, *args, table_hi=hi, **kw)
    out = pallas_walk_local(table, hi, *args, interpret=True, **kw)
    names = ("x", "lelem", "done", "exited", "pending")
    for name, a, b in zip(names, out[:5], ref[:5]):
        if not bool(jnp.all(a == b)):
            print(f"# FATAL: interpret parity gate — {name} not bitwise "
                  "vs walk_local", file=sys.stderr)
            sys.exit(1)
    flux_rel = float(
        jnp.max(jnp.abs(out[5] - ref[5])
                / jnp.maximum(jnp.abs(ref[5]), 1e-30))
    )
    if flux_rel > 1e-6:
        print(f"# FATAL: interpret parity gate — flux divergence "
              f"{flux_rel:.2e} outside the reassociation class",
              file=sys.stderr)
        sys.exit(1)
    pauses = int(jnp.sum(out[4] >= 0))
    exits = int(jnp.sum(out[3]))
    if pauses == 0 or exits == 0:
        print("# FATAL: interpret parity workload exercised no "
              "pauses/exits — the gate proves nothing", file=sys.stderr)
        sys.exit(1)
    return {"bitwise": True, "flux_max_rel": flux_rel,
            "pauses": pauses, "exits": exits, "particles": n}


def run_ab(
    n: int = N, div: int = DIV, moves: int = MOVES, trials: int = TRIALS,
    block_elems: int = BLOCK_ELEMS,
) -> dict:
    """Measure both engine arms; return the summary record (module
    docstring). Raises SystemExit on any gate failure — a silently
    corrupted arm must not report a rate."""
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from pumiumtally_tpu.ops.pallas_walk import modeled_walk_bytes
    from pumiumtally_tpu.utils.profiling import retrace_guard

    gate = _interpret_parity_gate()

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(29)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dests = [
        np.clip(src + rng.normal(scale=0.2, size=(n, 3)), 0.02, 0.98)
    ]
    for _ in range(moves - 1):
        dests.append(np.clip(
            dests[-1] + rng.normal(scale=0.2, size=(n, 3)), 0.02, 0.98
        ))

    def build(kernel):
        return PartitionedPumiTally(mesh, n, TallyConfig(
            walk_table_dtype="bfloat16", walk_kernel=kernel,
            walk_vmem_max_elems=block_elems, capacity_factor=3.0,
            check_found_all=False,
        ))

    def drive(t):
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())
        jax.block_until_ready(t.flux)

    def fenced_ms(t):
        t.CopyInitialPosition(src.reshape(-1).copy())
        jax.block_until_ready(t.flux)
        total = 0.0
        for d in dests:
            t0 = time.perf_counter()
            t.MoveToNextLocation(None, d.reshape(-1).copy())
            jax.block_until_ready(t.flux)
            total += time.perf_counter() - t0
        return total / len(dests) * 1e3

    # Warmup: TWO passes per arm — the second pass compiles one more
    # cascade-phase variant (re-sourcing on a warm engine), and the
    # timed window must see none.
    t_gather = build("gather")
    drive(t_gather)
    drive(t_gather)
    with retrace_guard(raise_on_exceed=False) as guard:
        t_pallas = build("pallas")
        drive(t_pallas)
        drive(t_pallas)
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            times = {"gather": [], "pallas": []}
            for _ in range(trials):
                for k, t in (("gather", t_gather), ("pallas", t_pallas)):
                    t0 = time.perf_counter()
                    drive(t)
                    times[k].append(time.perf_counter() - t0)
    assert t_pallas.engine.use_pallas_walk
    assert t_pallas.engine.blocks_per_chip > 1  # really streaming

    # Cross-arm gates on the timed arms: positions/elements BITWISE
    # (the kernel seam's own pin), flux in the reassociation class.
    if not bool(jnp.all(jnp.asarray(t_pallas.positions)
                        == jnp.asarray(t_gather.positions))):
        print("# FATAL: pallas arm positions not bitwise vs gather arm",
              file=sys.stderr)
        sys.exit(1)
    if not bool(jnp.all(jnp.asarray(t_pallas.elem_ids)
                        == jnp.asarray(t_gather.elem_ids))):
        print("# FATAL: pallas arm elem_ids not bitwise vs gather arm",
              file=sys.stderr)
        sys.exit(1)
    f_g = np.asarray(t_gather.flux, np.float64)
    f_p = np.asarray(t_pallas.flux, np.float64)
    rel = float(np.abs(f_p - f_g).max()
                / max(np.abs(f_g).max(), 1e-30))
    if rel > CROSS_ARM_RTOL:
        print(f"# FATAL: cross-arm flux divergence {rel:.2e}",
              file=sys.stderr)
        sys.exit(1)
    expect = float(sum(
        np.linalg.norm(
            np.asarray(b, np.float64) - np.asarray(a, np.float64), axis=1
        ).sum()
        for a, b in zip([src] + dests[:-1], dests)
    ))
    for k, f in (("gather", f_g), ("pallas", f_p)):
        # Each drive (2 warmups + the timed trials) re-sources and
        # re-walks the same campaign, accumulating into one flux.
        per_pass = f.sum() / (2 + trials)
        crel = abs(per_pass - expect) / expect
        if crel > CONSERVATION_RTOL:
            print(f"# FATAL: {k} arm conservation off by {crel:.2e}",
                  file=sys.stderr)
            sys.exit(1)

    rate = {k: n * moves / float(np.median(ts))
            for k, ts in times.items()}
    return {
        "row": "pallas_walk",
        "gather_moves_per_sec": rate["gather"],
        "pallas_moves_per_sec": rate["pallas"],
        "speedup": rate["pallas"] / rate["gather"],
        "fenced_gather_ms_per_move": fenced_ms(t_gather),
        "fenced_pallas_ms_per_move": fenced_ms(t_pallas),
        "interpret_parity": gate,
        "backend": jax.default_backend(),
        "pallas_interpret_mode": jax.default_backend() not in (
            "tpu", "axon"
        ),
        "blocks_per_chip": int(t_pallas.engine.blocks_per_chip),
        "modeled_bytes_per_crossing": {
            "gather_f32": modeled_walk_bytes("gather"),
            "gather_bf16": modeled_walk_bytes("gather", "bfloat16"),
            "pallas_bf16": modeled_walk_bytes("pallas", "bfloat16"),
            "vmem_resident": modeled_walk_bytes("vmem"),
        },
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div ** 3, "moves": moves,
            "trials": trials, "block_elems": block_elems,
        },
    }


def main() -> None:
    if "--quick" in sys.argv:
        rec = run_ab(n=4096, div=6, moves=2, trials=1, block_elems=512)
    else:
        rec = run_ab()
    print(json.dumps(rec, default=float))


if __name__ == "__main__":
    main()
