"""Is one [N,4,3]x[N,3,2] einsum cheaper than two [N,4,3]x[N,3]?"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

N = 500_000
rng = np.random.default_rng(0)
fn = jnp.asarray(rng.normal(size=(N, 4, 3)), jnp.float32)
x = jnp.asarray(rng.normal(size=(N, 3)), jnp.float32)
d = jnp.asarray(rng.normal(size=(N, 3)), jnp.float32)
fo = jnp.asarray(rng.normal(size=(N, 4)), jnp.float32)

@jax.jit
def two(fn, fo, x, d):
    denom = jnp.einsum("nfc,nc->nf", fn, d)
    numer = fo - jnp.einsum("nfc,nc->nf", fn, x)
    return denom, numer

@jax.jit
def one(fn, fo, x, d):
    xd = jnp.stack([d, x], axis=-1)          # [N,3,2]
    both = jnp.einsum("nfc,nck->nfk", fn, xd)  # [N,4,2]
    return both[..., 0], fo - both[..., 1]

def t(f):
    o = f(fn, fo, x, d); jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(30): o = f(fn, fo, x, d)
    s = float(jnp.sum(o[0]) + jnp.sum(o[1]))  # real sync
    return (time.perf_counter() - t0) / 30, s

ta, sa = t(two); tb, sb = t(one)
print(f"two einsums: {ta*1e3:.2f} ms   fused: {tb*1e3:.2f} ms   (checks {sa:.1f} {sb:.1f})")
