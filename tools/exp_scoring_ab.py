"""Filtered-scoring overhead A/B (r10).

Two arms over the IDENTICAL disjoint-corridor box workload (same mesh,
same seeds, continue-mode moves; half the particles transport in
x < 0.5 at bin-0 energies, half in x > 0.5 at bin-1 energies — the
single-bin-per-element structure that makes the bin telescoping check
BITWISE, tests/test_scoring.py):

- ``off``: the default engine (TallyConfig() — no scoring code runs);
- ``on``:  ``scoring=ScoringSpec(EnergyFilter(2 bins),
  [flux, heating, events])`` with per-move ``energy=`` staging.

Reported, non-interactively (one JSON line — the r9 suite's
scoring_ab stage and bench.py's scoring row both consume it):

- both arms' moves/s and the relative scoring overhead;
- the fenced per-move cost delta (``scoring_ms_per_move``) — the
  whole hook: attribute staging + jitted bin resolution + the fused
  lane scatter riding every walk group;
- the BITWISE flux parity gate (scoring-on flux == scoring-off flux:
  the flux scatter is untouched by the hook) and the BITWISE bin
  telescoping gate (2-bin flux lanes sum == the flux lane), both
  asserted before any number is reported;
- the compiles-healthy contract: ``compiles.timed == 0`` — the
  scoring-armed walk and the ``score_bins`` resolution compile once
  each in the warmup moves, never inside the timed window.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _corridor_batches(rng, n: int, moves: int):
    """(src, [dests...], energy): disjoint half-box corridors with
    bin-disjoint energies (module docstring)."""
    half = n // 2

    def pts():
        p = np.empty((n, 3))
        p[:half] = rng.uniform(
            [0.05, 0.05, 0.05], [0.45, 0.95, 0.95], (half, 3)
        )
        p[half:] = rng.uniform(
            [0.55, 0.05, 0.05], [0.95, 0.95, 0.95], (n - half, 3)
        )
        return p

    energy = np.where(np.arange(n) < half, 0.5, 1.5)
    return pts(), [pts() for _ in range(moves)], energy


def run_ab(n: int = 100_000, div: int = 20, moves: int = 6,
           warmup: int = 2) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import (
        EnergyFilter,
        PumiTally,
        ScoringSpec,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(10)
    src, dests, energy = _corridor_batches(rng, n, warmup + moves)
    spec = ScoringSpec(
        filters=[EnergyFilter([0.0, 1.0, 2.0])],
        scores=["flux", "heating", "events"],
    )

    def build(scoring) -> PumiTally:
        return PumiTally(
            mesh, n,
            TallyConfig(scoring=scoring, check_found_all=False,
                        fenced_timing=False),
        )

    def drive(t, ds, scored: bool):
        for d in ds:
            t.MoveToNextLocation(
                None, d.reshape(-1).copy(),
                energy=energy if scored else None,
            )

    t_on = build(spec)
    with retrace_guard(raise_on_exceed=False) as guard:
        t_on.CopyInitialPosition(src.reshape(-1).copy())
        drive(t_on, dests[:warmup], True)
        jax.block_until_ready((t_on.flux, t_on.score_bank))
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            t0 = time.perf_counter()
            drive(t_on, dests[warmup:], True)
            jax.block_until_ready((t_on.flux, t_on.score_bank))
            on_s = time.perf_counter() - t0

    t_off = build(None)
    t_off.CopyInitialPosition(src.reshape(-1).copy())
    drive(t_off, dests[:warmup], False)
    jax.block_until_ready(t_off.flux)
    t0 = time.perf_counter()
    drive(t_off, dests[warmup:], False)
    jax.block_until_ready(t_off.flux)
    off_s = time.perf_counter() - t0

    # Parity gates, enforced where the measurement happens
    # (RuntimeError, not sys.exit — bench.py wraps this row in a
    # best-effort except, exp_stats_ab precedent).
    if not bool(jnp.all(t_on.flux == t_off.flux)):
        raise RuntimeError(
            "scoring-on flux diverged bitwise from scoring-off flux"
        )
    arr = np.asarray(t_on.score_bank).reshape(mesh.nelems, 2, 3)
    if not np.array_equal(arr[:, :, 0].sum(axis=1),
                          np.asarray(t_on.flux)):
        raise RuntimeError(
            "2-bin flux lanes do not telescope bitwise to the flux lane"
        )

    moves_total = n * moves
    return {
        "row": "scoring",
        "on_moves_per_sec": moves_total / on_s,
        "off_moves_per_sec": moves_total / off_s,
        "scoring_overhead_pct": (on_s - off_s) / off_s * 100.0,
        "scoring_ms_per_move": (on_s - off_s) / moves * 1e3,
        "flux_parity_bitwise": True,
        "telescoping_bitwise": True,
        "events_total": float(arr[:, :, 2].sum()),
        "lanes": {"n_bins": 2, "n_scores": 3,
                  "bank_elems": int(mesh.nelems * 6)},
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div**3, "moves": moves,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 6))
    print(json.dumps(run_ab(n=n, div=div, moves=moves), default=float))


if __name__ == "__main__":
    main()
