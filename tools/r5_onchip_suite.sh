#!/bin/bash
# Round-5 on-chip suite: fired by tools/r5_probe_loop.sh the moment the
# TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK headline bench
# runs first (a short window must still yield a fresh cached
# measurement), then the full known-good bench, then the new-engine
# experiments; the production-VMEM compile+measure goes LAST because
# its remote compile request is the prime wedge suspect (r4's helper
# hung rather than erroring).
set -u
OUT=/tmp/r5_onchip
mkdir -p "$OUT"
cd /root/repo
echo "suite started $(date)" > "$OUT/status"
STAGES=""
write_digest() {
  # Regenerated after EVERY stage so a window that closes mid-suite
  # still leaves a digest covering what ran.
  local DG=/root/repo/tools/r5_onchip/digest.md
  {
    echo "# r5 on-chip suite digest"
    cat "$OUT/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|moves/s|OK|FAILED|FATAL|FAILURE|rc=' "$OUT/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG" 2>/dev/null
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$OUT/status"
  mkdir -p /root/repo/tools/r5_onchip
  cp "$OUT/$name.log" /root/repo/tools/r5_onchip/$name.log 2>/dev/null
  cp "$OUT/status" /root/repo/tools/r5_onchip/status 2>/dev/null
  STAGES="$STAGES $name"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success) for the
# round record. The full bench then overwrites it with the complete
# row set.
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
run blocked    2400 python tools/exp_r5_blocked.py 500000 4
run native     1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
run vmem_prod  1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$OUT/status"
cp "$OUT/status" /root/repo/tools/r5_onchip/status 2>/dev/null
write_digest
