#!/usr/bin/env python
"""Thin repo-root wrapper for the jaxlint CLI.

Equivalent to ``python -m pumiumtally_tpu.analysis`` but runnable from
a checkout WITHOUT jax/numpy installed (the CI jaxlint job runs on a
bare Python): importing ``pumiumtally_tpu.analysis`` normally first
executes the package ``__init__``, which imports jax. The stub parent
module below gives ``pumiumtally_tpu`` a ``__path__`` without running
its ``__init__``, so only the stdlib-only analysis subpackage loads.
See docs/STATIC_ANALYSIS.md.
"""

import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "pumiumtally_tpu" not in sys.modules:
    _stub = types.ModuleType("pumiumtally_tpu")
    _stub.__path__ = [os.path.join(_REPO, "pumiumtally_tpu")]
    sys.modules["pumiumtally_tpu"] = _stub

from pumiumtally_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
