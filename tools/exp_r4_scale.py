"""BASELINE config-2 on-chip scale rows: walk rate + memory headroom
on the ~1M-tet assembly lattice (round-4 item 6).

Measures, on whatever accelerator is attached:
  - mesh build + precompute + upload wall time;
  - continue-mode tallied move rate at N particles (the headline
    metric's protocol) for a few segment lengths (crossings/move
    scales with length — the rate story needs both);
  - device memory in use after upload (walk table [E,20] f32 ~80 MB at
    1M tets) via jax's memory stats when the backend exposes them;
  - the same on the 48k-tet box for a same-run reference point.

Usage:  python tools/exp_r4_scale.py [n_particles]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.mesh.pincell import build_lattice


def mem_mb() -> str:
    try:
        stats = jax.devices()[0].memory_stats()
        if not stats:
            return "n/a"
        return f"{stats.get('bytes_in_use', 0) / 1e6:.0f} MB in use"
    except Exception:  # noqa: BLE001 — diagnostic only
        return "n/a"


def drive(mesh, box, n, mean_step, moves=4, seed=0) -> float:
    rng = np.random.default_rng(seed)
    t = PumiTally(mesh, n, TallyConfig(check_found_all=False,
                                       fenced_timing=False))
    src = rng.uniform(0.05, 0.95, (n, 3)) * box
    t.CopyInitialPosition(src.reshape(-1).copy())
    d = src
    # warmup (compile)
    d = np.clip(d + rng.normal(scale=mean_step / np.sqrt(3), size=d.shape),
                0.02 * box, 0.98 * box)
    t.MoveToNextLocation(None, d.reshape(-1).copy())
    float(jnp.sum(t.flux))
    t0 = time.perf_counter()
    for _ in range(moves):
        d = np.clip(d + rng.normal(scale=mean_step / np.sqrt(3),
                                   size=d.shape),
                    0.02 * box, 0.98 * box)
        t.MoveToNextLocation(None, d.reshape(-1).copy())
    float(jnp.sum(t.flux))
    return n * moves / (time.perf_counter() - t0)


def main(n: int) -> None:
    print(f"backend={jax.default_backend()}  start mem: {mem_mb()}")

    t0 = time.perf_counter()
    mesh48 = build_box(1, 1, 1, 20, 20, 20, dtype=jnp.float32)
    print(f"box 48k built in {time.perf_counter() - t0:.2f}s")
    for step in (0.25, 0.05):
        r = drive(mesh48, np.ones(3), n, step, seed=1)
        print(f"box48k  step={step}: {r / 1e6:.2f}M moves/s  ({mem_mb()})")

    t0 = time.perf_counter()
    mesh1m, _, _ = build_lattice(10, 10, n_theta=24, n_rings_fuel=4,
                                 n_rings_pad=4, nz=10, dtype=jnp.float32)
    build_s = time.perf_counter() - t0
    E = mesh1m.nelems
    print(f"lattice {E} tets built+precomputed in {build_s:.2f}s; "
          f"table ~{E * 20 * 4 / 1e6:.0f} MB f32  ({mem_mb()})")
    box = np.array([10 * 1.26, 10 * 1.26, 1.0])
    for step in (0.25, 0.05):
        r = drive(mesh1m, box, n, step, seed=2)
        print(f"lattice1M step={step}: {r / 1e6:.2f}M moves/s  ({mem_mb()})")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 500_000)
