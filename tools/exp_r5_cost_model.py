"""XLA cost-model A/B: monolithic vs gather-blocked walk programs.

Compiles both engines' tallied-move programs for a real v5e:1x1x1
target (chipless, local libtpu) on the SAME workload shape round 4
used for the vmem cost row (3072-tet box, 4096 particles, 256-iteration
budget) and prints `cost_analysis()` bytes/FLOPs. While-loop trip
counts make the absolute numbers upper bounds; the RELATIVE comparison
at identical budgets is the signal (r4: gather 689 MB vs vmem 162 MB
accessed on the 4-chip phase — the bet this round's gather sub-split
chases from the other side, table residency instead of MXU one-hot).

Usage: python tools/exp_r5_cost_model.py [divs] [n]
"""

from __future__ import annotations

import os
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

from functools import partial  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

MAX_ITERS = 256


def _sharding(topology="v5e:1x1x1"):
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology,
        chips_per_host_bounds=[1, 1, 1],
    )
    return NamedSharding(topologies.make_mesh(topo, (1,), ("dp",)), P())


def _report(label, compiled):
    ca = compiled.cost_analysis()
    if not ca:
        print(f"{label}: no cost analysis available")
        return
    print(f"{label}: {ca.get('bytes accessed', 0) / 1e6:.0f} MB accessed, "
          f"{ca.get('flops', 0) / 1e6:.0f} MFLOP", flush=True)


def main(divs: int, n: int) -> None:
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.api.tally import move_step_continue
    from pumiumtally_tpu.parallel.partition import PartitionedEngine

    sh = _sharding()
    mesh = build_box(1, 1, 1, divs, divs, divs, dtype=jnp.float32)
    E = mesh.nelems
    print(f"workload: {E} tets, {n} particles, {MAX_ITERS}-iter budget",
          flush=True)

    # Monolithic continue-mode move (the r1-r4 headline program).
    spec = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
        jnp.shape(a), jnp.result_type(a), sharding=sh
    )
    f = partial(move_step_continue, tol=1e-6, max_iters=MAX_ITERS)
    state = dict(
        x=jnp.zeros((n, 3), jnp.float32),
        elem=jnp.zeros((n,), jnp.int32),
        dests=jnp.zeros((n, 3), jnp.float32),
        flying=jnp.ones((n,), jnp.int8),
        weights=jnp.ones((n,), jnp.float32),
        flux=jnp.zeros((E,), jnp.float32),
    )
    lowered = jax.jit(
        lambda x, elem, dests, fly, w, flux: f(
            mesh, x, elem, dests, fly, w, flux
        )
    ).lower(*(spec(state[k]) for k in
              ("x", "elem", "dests", "flying", "weights", "flux")))
    _report("monolithic continue move", lowered.compile())

    # Gather-blocked phase at the same per-block scale as the headline
    # config (bound E//8 -> 8 blocks).
    tmesh = sh.mesh
    eng = PartitionedEngine(
        mesh, tmesh, n, capacity_factor=2.0, tol=1e-6,
        max_iters=MAX_ITERS, max_rounds=8, check_found_all=False,
        vmem_walk_max_elems=max(1, E // 8), block_kernel="gather",
    )
    print(f"blocked engine: {eng.blocks_per_chip} blocks x L={eng.part.L}",
          flush=True)
    phase = eng._phase_program(tally=True)
    espec = lambda a: None if a is None else jax.ShapeDtypeStruct(  # noqa: E731
        a.shape, a.dtype, sharding=sh
    )
    args = (espec(eng.part.table), espec(eng.part.adj_int),
            {k: espec(v) for k, v in eng.state.items()},
            espec(eng.flux_padded))
    _report("gather-blocked phase", phase.lower(*args).compile())


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8,
         int(sys.argv[2]) if len(sys.argv) > 2 else 4096)
