"""A/B: f32 single-tier vs bf16 two-tier walk tables, at bench shape.

The two-tier bet (docs/PERF_NOTES.md "Table precision tiers"): the hot
loop's floor is the random-row gather of the packed f32 [E,20] walk
table (~80 B/crossing). The bf16 SELECT tier halves the row the
per-crossing gather touches (32 B planes + 16 B int32 adjacency), and
ONE full-precision refinement gather of the winning face's plane
(16 B) keeps track lengths and committed positions at working-dtype
accuracy — select-in-bf16 / commit-in-f32 (docs/DESIGN.md invariant).

This tool measures both arms on the CURRENT backend with the
bench-shaped continue-mode workload (same mesh family, same clipped-
gaussian steps) at the raw kernel level — no facade/staging noise:

1. correctness first: both arms must pass the conservation gate, and
   the flux L1 divergence between them is reported (the benign
   tie-class reattribution, expected ~1e-3 relative);
2. rates: timed passes INTERLEAVED between arms (PERF_NOTES r5
   measurement note: back-to-back whole-arm runs fold frequency/cache
   ramp into the first arm), median per arm;
3. bytes provenance: select-tier table bytes (the per-crossing random
   gather's working set — the number that must halve), total walk-
   geometry bytes per arm, and the modeled B/crossing.

Prints one JSON line; ``run_ab`` is also called in-process by
bench.py's ``table_precision`` row. Run on CPU now (the recorded
PERF_NOTES numbers) and unchanged in the next chip window
(tools/r6_onchip_suite.sh, under the suite's chip-window interlock).

Usage:
    JAX_PLATFORMS=cpu python tools/exp_table_precision_ab.py [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N = int(os.environ.get("PUMIUMTALLY_AB_N", 200_000))
DIV = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))  # 20^3 cells = 48k tets
MOVES = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 3))
TRIALS = int(os.environ.get("PUMIUMTALLY_AB_TRIALS", 3))
MEAN_STEP = 0.25
CONSERVATION_RTOL = 1e-6


def _workload(n: int, moves: int, dtype):
    rng = np.random.default_rng(0)
    pts = [rng.uniform(0.05, 0.95, (n, 3))]
    for _ in range(moves + 1):
        step = rng.normal(scale=MEAN_STEP / np.sqrt(3.0), size=(n, 3))
        pts.append(np.clip(pts[-1] + step, 0.02, 0.98))
    import jax.numpy as jnp

    return [jnp.asarray(p, dtype) for p in pts]


def geometry_bytes(mesh) -> dict:
    """Byte provenance of one arm's walk-geometry tables. ``select``
    is the working set the per-crossing random row gather touches —
    the quantity the bf16 tier halves (and the table that must be
    resident for the gather sub-split's small-table regime);
    ``refine`` is the per-face tier whose winning row (plane + adj
    lane) is the ONLY other per-crossing gather. The f32 arm's
    adjacency rides inside its packed row; the bf16 arm's rides the
    refinement row."""
    if mesh.walk_table_lo is not None:
        sel = mesh.walk_table_lo.nbytes
        refine = mesh.walk_table_hi.nbytes
        lo_row = (
            mesh.walk_table_lo.dtype.itemsize * mesh.walk_table_lo.shape[1]
        )
        hi_row = (
            mesh.walk_table_hi.dtype.itemsize * mesh.walk_table_hi.shape[1]
        )
        per_crossing = lo_row + hi_row  # select row + ONE refined face
    else:
        sel = mesh.walk_table.nbytes
        refine = 0
        row = mesh.walk_table.dtype.itemsize * mesh.walk_table.shape[1]
        per_crossing = row
    return {
        "select_table_bytes": int(sel),
        "refine_table_bytes": int(refine),
        "total_bytes": int(sel + refine),
        "modeled_bytes_per_crossing": int(per_crossing),
    }


def run_ab(
    n: int = N, div: int = DIV, moves: int = MOVES, trials: int = TRIALS
) -> dict:
    """Measure both arms; return the summary record (see module
    docstring). Raises SystemExit on a conservation-gate failure —
    a silently corrupted arm must not report a rate."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from pumiumtally_tpu import TallyConfig, build_box
    from pumiumtally_tpu.api.tally import _localize_step
    from pumiumtally_tpu.ops.walk import walk

    cfg = TallyConfig()
    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    mesh_lo = mesh.with_lowp_tables()
    dtype = mesh.coords.dtype
    tol = cfg.resolved_tolerance(dtype)
    max_iters = cfg.resolved_max_iters(mesh.nelems)
    pts = _workload(n, moves, dtype)

    # One shared localization: identical start state for both arms.
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    x0, e0, done, _ = _localize_step(
        mesh, jnp.broadcast_to(c0, (n, 3)), jnp.zeros((n,), jnp.int32),
        pts[0], tol=tol, max_iters=max_iters,
    )
    assert bool(jnp.all(done)), "A/B workload failed to localize"
    fly = jnp.ones((n,), jnp.int8)
    w = jnp.ones((n,), dtype)

    arms = {
        "f32": (mesh, "float32"),
        "bf16": (mesh_lo, "bfloat16"),
    }
    progs = {
        k: jax.jit(partial(
            walk, tally=True, tol=tol, max_iters=max_iters, table_dtype=td,
        ))
        for k, (_, td) in arms.items()
    }

    def run_pass(k):
        m, _ = arms[k]
        x, e, flux = x0, e0, jnp.zeros((mesh.nelems,), dtype)
        t0 = time.perf_counter()
        for mv in range(1, moves + 1):
            r = progs[k](m, x, e, pts[mv], fly, w, flux)
            x, e, flux = r.x, r.elem, r.flux
        total = float(jnp.sum(flux))  # the sync
        return time.perf_counter() - t0, flux, e, total

    # Warmup (compiles) + correctness capture, then interleaved trials.
    results = {k: run_pass(k) for k in arms}
    expect = sum(
        float(np.linalg.norm(
            np.asarray(pts[mv], np.float64)
            - np.asarray(pts[mv - 1], np.float64),
            axis=1,
        ).sum())
        for mv in range(1, moves + 1)
    )
    cons = {}
    for k, (_, flux, _, total) in results.items():
        rel = abs(total - expect) / expect
        cons[k] = rel
        if rel > CONSERVATION_RTOL:
            print(f"# FATAL: {k} arm conservation off by {rel:.2e}",
                  file=sys.stderr)
            sys.exit(1)
    f_f32 = np.asarray(results["f32"][1], np.float64)
    f_bf = np.asarray(results["bf16"][1], np.float64)
    e_f32 = np.asarray(results["f32"][2])
    e_bf = np.asarray(results["bf16"][2])

    times = {k: [] for k in arms}
    for _ in range(trials):
        for k in arms:  # interleaved — see module docstring
            times[k].append(run_pass(k)[0])
    rate = {k: n * moves / float(np.median(ts)) for k, ts in times.items()}

    bytes_ab = {k: geometry_bytes(m) for k, (m, _) in arms.items()}
    return {
        "row": "table_precision",
        "f32_moves_per_sec": rate["f32"],
        "bf16_moves_per_sec": rate["bf16"],
        "speedup": rate["bf16"] / rate["f32"],
        "select_table_bytes_f32": bytes_ab["f32"]["select_table_bytes"],
        "select_table_bytes_bf16": bytes_ab["bf16"]["select_table_bytes"],
        "select_bytes_ratio": (
            bytes_ab["bf16"]["select_table_bytes"]
            / bytes_ab["f32"]["select_table_bytes"]
        ),
        "bytes": bytes_ab,
        "conservation_rel_err": cons,
        "flux_l1_rel_divergence": float(np.abs(f_f32 - f_bf).sum() / expect),
        "elem_divergence_frac": float(np.mean(e_f32 != e_bf)),
        "workload": {"particles": n, "mesh_tets": 6 * div ** 3,
                     "moves": moves, "trials": trials},
    }


def main() -> None:
    if "--quick" in sys.argv:
        rec = run_ab(n=20_000, div=6, moves=2)
    else:
        rec = run_ab()
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
