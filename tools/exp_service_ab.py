"""Service-layer overhead A/B: 1-session service vs direct facade (r11).

Three arms over the IDENTICAL box workload (same mesh, same seeds,
same per-batch protocol: one CopyInitialPosition + ``moves``
continue-mode moves per source batch):

- ``direct``: the bare monolithic facade, unfenced
  (``fenced_timing=False`` — the established bench posture: calls
  return at dispatch);
- ``service``: the same facade behind a 1-session ``TallyService``,
  unfenced — the PIPELINED serving path: submit-time prepack +
  validation on the client thread, futures, the worker's facade call
  returning at dispatch, so move k+1's staging overlaps move k's
  device compute;
- ``service_fenced``: the same served session over a
  ``fenced_timing=True`` facade — every move synchronizes before the
  next op runs, so the fenced-vs-pipelined spread is the measured
  value of cross-move overlap under the service.

Reported, non-interactively (one JSON line — bench.py's "service" row
consumes it): all three rates, the service-vs-direct overhead (the
serving tax: queue hops + one extra owned host copy per buffer), the
pipelined/fenced speedup, and the compiles-healthy contract
(``compiles.timed == 0`` — the service adds NO jitted entry points;
every compile happens in the warmup batches, exactly the facade's
own).

Flux parity between the direct and served arms is asserted BITWISE
before any number is reported — the determinism-under-concurrency
contract's single-session corner, enforced where the measurement
happens.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _make_batches(rng, n: int, batches: int, moves: int):
    src = rng.uniform(0.1, 0.9, (n, 3))
    segs = [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)]
    return [(src, segs) for _ in range(batches)]


def _drive_direct(t, work):
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())


def _drive_handle(h, work, timeout=600):
    """Submit the whole campaign through the bounded queue, retrying
    on backpressure (the documented client reaction: the refused op
    was never queued). The futures resolve in order; waiting on the
    last is waiting on all."""
    import time

    from pumiumtally_tpu import ServiceBusyError

    def submit(fn, *args):
        while True:
            try:
                return fn(*args)
            except ServiceBusyError:
                time.sleep(0.0005)

    futs = []
    for src, dests in work:
        futs.append(submit(h.copy_initial_position,
                           src.reshape(-1).copy()))
        for d in dests:
            futs.append(submit(h.move, None, d.reshape(-1).copy()))
    for f in futs:
        f.result(timeout=timeout)


def run_ab(
    n: int = 100_000,
    div: int = 20,
    moves: int = 2,
    batches: int = 8,
) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import (
        PumiTally,
        TallyConfig,
        TallyService,
        build_box,
    )
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(17)
    work = _make_batches(rng, n, batches, moves)
    cfg = dict(check_found_all=False, fenced_timing=False)
    # One batch in flight end to end (source + every move of the next
    # batch stages while the previous walks).
    queue_depth = moves + 1

    # Arm 1: direct facade.
    t_direct = PumiTally(mesh, n, TallyConfig(**cfg))
    _drive_direct(t_direct, work[:2])  # warmup: compiles happen here
    jax.block_until_ready(t_direct.flux)
    t0 = time.perf_counter()
    _drive_direct(t_direct, work[2:])
    jax.block_until_ready(t_direct.flux)
    direct_s = time.perf_counter() - t0

    # Arm 2: 1-session service, pipelined (unfenced facade).
    with retrace_guard(raise_on_exceed=False) as guard:
        with TallyService() as svc:
            h = svc.open_session(PumiTally(mesh, n, TallyConfig(**cfg)),
                                 max_queue=queue_depth)
            _drive_handle(h, work[:2])
            h.flux().result(timeout=600)  # fence the warmup
            with retrace_guard(raise_on_exceed=False) as timed_guard:
                t0 = time.perf_counter()
                _drive_handle(h, work[2:])
                flux_served = h.flux().result(timeout=600)
                service_s = time.perf_counter() - t0

    # Parity gate: a 1-session service is the bare facade plus queues
    # — BITWISE, or the serving layer corrupted a campaign.
    if not bool(jnp.all(t_direct.flux == jnp.asarray(flux_served))):
        raise RuntimeError(
            "1-session service flux diverged bitwise from the direct "
            "facade"
        )

    # Arm 3: served but FENCED facade (no cross-move pipelining).
    with TallyService() as svc:
        h = svc.open_session(
            PumiTally(mesh, n, TallyConfig(check_found_all=False,
                                           fenced_timing=True)),
            max_queue=queue_depth,
        )
        _drive_handle(h, work[:2])
        h.flux().result(timeout=600)
        t0 = time.perf_counter()
        _drive_handle(h, work[2:])
        h.flux().result(timeout=600)
        fenced_s = time.perf_counter() - t0

    moves_total = n * moves * (batches - 2)
    return {
        "row": "service",
        "direct_moves_per_sec": moves_total / direct_s,
        "service_moves_per_sec": moves_total / service_s,
        "service_fenced_moves_per_sec": moves_total / fenced_s,
        "service_overhead_pct": (service_s - direct_s) / direct_s * 100.0,
        "pipeline_speedup": fenced_s / service_s,
        "flux_parity_bitwise": True,
        "queue_depth": queue_depth,
        # The service adds no entry points: every compile is the
        # facade's own, in warmup — never in the timed window.
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 8))
    print(json.dumps(run_ab(n=n, div=div, moves=moves, batches=batches),
                     default=float))


if __name__ == "__main__":
    main()
