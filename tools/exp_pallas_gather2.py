"""Probe 2: take_along_axis-based dynamic gather in Mosaic."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

E, W, T = 48000, 32, 8192

def kern(tab_ref, idx_ref, out_ref):
    idx2 = jnp.broadcast_to(idx_ref[:][:, None], (T, W))
    out_ref[:] = jnp.take_along_axis(tab_ref[:], idx2, axis=0)

@jax.jit
def gather(tab, idx):
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((T, W), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(tab, idx)

rng = np.random.default_rng(0)
tab = jnp.asarray(rng.normal(size=(E, W)), jnp.float32)
idx = jnp.asarray(rng.integers(0, E, T), jnp.int32)
try:
    out = gather(tab, idx)
    ok = np.allclose(np.asarray(out), np.asarray(tab)[np.asarray(idx)])
    print("take_along_axis gather works:", ok)
    for _ in range(3):
        out = gather(tab, idx)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(50):
        out = gather(tab, idx)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / 50
    print(f"gather {T} rows x {W} f32: {dt*1e6:.1f} us -> {T/dt/1e6:.1f} Mrows/s")
except Exception as e:
    print("FAILED:", type(e).__name__, str(e)[:800])
