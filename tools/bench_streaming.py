"""BASELINE config 5: 10M-particle/batch streaming on the real chip."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax.numpy as jnp, numpy as np
from pumiumtally_tpu import StreamingTally, TallyConfig, build_box

N, CHUNK, DIV = 10_000_000, 1_000_000, 20
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
t = StreamingTally(mesh, N, chunk_size=CHUNK,
                   config=TallyConfig(check_found_all=False))
rng = np.random.default_rng(0)
src = rng.uniform(0.05, 0.95, (N, 3))
t0 = time.perf_counter()
t.CopyInitialPosition(src.reshape(-1))
print(f"localize 10M: {time.perf_counter()-t0:.1f}s (async dispatch)")
d = np.clip(src + rng.normal(scale=0.25/np.sqrt(3), size=(N, 3)), 0.02, 0.98)
t0 = time.perf_counter()
t.MoveToNextLocation(None, d.reshape(-1))
total = float(jnp.sum(t.flux))  # real sync
dt = time.perf_counter() - t0
expect = float(np.linalg.norm(d - src, axis=1).sum())
print(f"move 10M: {dt:.1f}s -> {N/dt/1e6:.2f}M moves/s; "
      f"conservation rel={abs(total-expect)/expect:.2e}")
