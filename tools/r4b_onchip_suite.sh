#!/bin/bash
# Round-4 second-window suite: fires once when the TPU tunnel recovers
# from the 04:33 UTC re-wedge. ORDER MATTERS: the clean bench re-run
# (uncontended headline) comes first because it is known-good; the
# production-VMEM Mosaic compile goes LAST because its compile request
# is the prime suspect for the re-wedge (the helper hung rather than
# erroring on the third attempt).
set -u
OUT=/tmp/r4b_onchip
mkdir -p "$OUT"
cd /root/repo
echo "suite started $(date)" > "$OUT/status"
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$OUT/status"
  mkdir -p /root/repo/tools/r4_onchip
  cp "$OUT/$name.log" /root/repo/tools/r4_onchip/r4b_$name.log 2>/dev/null
  cp "$OUT/status" /root/repo/tools/r4_onchip/r4b_status 2>/dev/null
}
run bench_clean 2700 python bench.py
run native     1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
run vmem_prod  1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$OUT/status"
cp "$OUT/status" /root/repo/tools/r4_onchip/r4b_status 2>/dev/null
