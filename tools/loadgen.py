"""Scripted-client load generator for the serving layer (round 20).

Drives hundreds of OpenMC-style clients — open, then batches of
source + moves, then close — against a ``pumiumtally serve`` worker
or a ``pumiumtally route`` router over the NDJSON socket protocol,
and reports the heavy-traffic numbers ROADMAP item 1 asks for:

- served moves/s (completed move ops across all clients / wall time);
- p50/p99 submit→resolve latency per move, client-observed (the
  request/reply round trip of a ``wait=true`` move — queueing, DRR
  turn, device walk, ack);
- per-lane fairness: Jain's index J = (Σx)² / (n·Σx²) over each
  priority lane's per-client served-move counts (1.0 = perfectly
  fair, 1/n = one client got everything);
- refusal counts: per-session busy retries and service-wide admission
  refusals (``"overloaded": true`` replies), plus hard errors.

The SCHEDULE is deterministic given ``seed``: Poisson arrivals
(exponential inter-arrival gaps), per-client priorities drawn from
``priority_mix``, and per-client campaign positions all come from
``numpy.random.default_rng`` seeded with (seed, client index) — so a
bench row can replay client 0's exact campaign solo and gate on
bitwise flux parity (bench.py ``service_load``). Timing, and
therefore the reported rates/latencies, is of course load- and
host-dependent; the WORK is not.

Session churn is inherent: clients arrive over ~clients/rate seconds,
run finite campaigns, close, and disconnect, so the service sees
opens and closes throughout the run, not one static fleet.

Pure stdlib + numpy on purpose — the load generator must be runnable
against a remote service from a host with no jax installed, and keeps
the client side honest: everything it measures crosses the wire.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_PRIORITIES = ("high", "normal", "low")


# -- wire helpers (standalone twins of service/server.py's; importing
# them from there would drag in the full service stack + jax) ---------
def _b64_f64(a) -> str:
    return base64.b64encode(
        np.ascontiguousarray(a, dtype="<f8").tobytes()
    ).decode("ascii")


def _b64_i8(a) -> str:
    return base64.b64encode(
        np.ascontiguousarray(a, dtype="<i1").tobytes()
    ).decode("ascii")


def _dec_f64(payload: str) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(payload), dtype="<f8"
    ).copy()


def client_campaign(seed: int, idx: int, particles: int, batches: int,
                    moves: int) -> List[Tuple[np.ndarray, List[np.ndarray]]]:
    """Client ``idx``'s deterministic campaign: ``batches`` entries of
    (source positions [3n], [dest positions [3n]] * moves), every
    coordinate in (0.01, 0.99) of the unit box scaled by the mesh —
    the same generator bench.py replays solo for the parity gate."""
    rng = np.random.default_rng([int(seed), int(idx)])
    return [
        (rng.random(3 * particles) * 0.98 + 0.01,
         [rng.random(3 * particles) * 0.98 + 0.01
          for _ in range(moves)])
        for _ in range(batches)
    ]


def jain(xs: List[int]) -> Optional[float]:
    """Jain's fairness index over per-client totals (None when the
    lane is empty, 1.0 for a single client by construction)."""
    if not xs:
        return None
    s = float(sum(xs))
    ss = float(sum(x * x for x in xs))
    if ss == 0.0:
        return 1.0  # nobody served anything: vacuously even
    return (s * s) / (len(xs) * ss)


class _ClientResult:
    __slots__ = ("priority", "moves_done", "latencies", "busy_retries",
                 "overload_refusals", "error", "flux")

    def __init__(self, priority: str):
        self.priority = priority
        self.moves_done = 0
        self.latencies: List[float] = []  # seconds, per served move
        self.busy_retries = 0
        self.overload_refusals = 0
        self.error: Optional[str] = None
        self.flux: Optional[np.ndarray] = None


def _rpc(f, req: dict) -> dict:
    f.write(json.dumps(req).encode("utf-8") + b"\n")
    f.flush()
    line = f.readline()
    if not line:
        raise ConnectionError("server closed the connection")
    return json.loads(line.decode("utf-8"))


def _rpc_admitted(f, req: dict, res: _ClientResult, *,
                  max_retries: int, retry_sleep: float) -> dict:
    """One op with retry-on-refusal: busy (per-session queue full) and
    overloaded (service admission budget) replies re-send the same
    bytes after a short sleep — both refusals leave server-side state
    untouched, which is exactly what makes blind resend correct."""
    for _ in range(int(max_retries)):
        r = _rpc(f, req)
        if r.get("ok"):
            return r
        if r.get("busy"):
            res.busy_retries += 1
        elif r.get("overloaded"):
            res.overload_refusals += 1
        else:
            raise RuntimeError(
                f"{r.get('error')}: {r.get('message')}"
            )
        time.sleep(retry_sleep)
    raise RuntimeError(
        f"op {req.get('op')!r} refused {max_retries} times "
        "(busy/overloaded): service never admitted it"
    )


def _run_client(host: str, port: int, idx: int, res: _ClientResult,
                t_start: float, *, seed: int, particles: int,
                batches: int, moves: int, facade: str,
                chunk_size: Optional[int], mesh_box, collect_flux: bool,
                max_retries: int, retry_sleep: float) -> None:
    delay = t_start - time.perf_counter()
    if delay > 0:
        time.sleep(delay)
    work = client_campaign(seed, idx, particles, batches, moves)
    ones = np.ones(particles, dtype=np.int8)
    with socket.create_connection((host, int(port))) as conn:
        f = conn.makefile("rwb")
        open_req: Dict[str, Any] = {
            "op": "open", "facade": facade,
            "num_particles": particles, "priority": res.priority,
            "mesh": {"box": list(mesh_box)},
            # Deep enough that one client can pipeline a full batch;
            # global pressure is the admission budget's job.
            "max_queue": moves + 2,
        }
        if chunk_size is not None:
            open_req["chunk_size"] = int(chunk_size)
        r = _rpc_admitted(f, open_req, res, max_retries=max_retries,
                          retry_sleep=retry_sleep)
        sid = r["session"]
        for src, dests in work:
            _rpc_admitted(
                f, {"op": "source", "session": sid,
                    "positions": _b64_f64(src)},
                res, max_retries=max_retries, retry_sleep=retry_sleep,
            )
            for d in dests:
                req = {"op": "move", "session": sid,
                       "dests": _b64_f64(d), "flying": _b64_i8(ones),
                       "wait": True}
                t0 = time.perf_counter()
                _rpc_admitted(f, req, res, max_retries=max_retries,
                              retry_sleep=retry_sleep)
                res.latencies.append(time.perf_counter() - t0)
                res.moves_done += 1
        if collect_flux:
            r = _rpc(f, {"op": "flux", "session": sid})
            if not r.get("ok"):
                raise RuntimeError(
                    f"flux failed: {r.get('message')}"
                )
            res.flux = _dec_f64(r["flux"])
        _rpc(f, {"op": "close", "session": sid})


def _quantile(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    a = sorted(xs)
    hi = len(a) - 1
    return a[min(hi, int(p * hi + 0.5))]


def run_load(host: str, port: int, *, clients: int = 100,
             rate: float = 200.0, particles: int = 64,
             batches: int = 1, moves: int = 2, facade: str = "mono",
             chunk_size: Optional[int] = None,
             mesh_box=(1.0, 1.0, 1.0, 3, 3, 3),
             priority_mix: Tuple[float, float, float] = (0.2, 0.6, 0.2),
             seed: int = 0, collect_flux: int = 0,
             max_retries: int = 2000, retry_sleep: float = 0.002,
             timeout: float = 600.0) -> Dict[str, Any]:
    """Run the load and return the report dict (see module docstring).

    Args:
      host, port: a ``serve`` worker or a ``route`` router.
      clients: scripted clients total (each: open → ``batches`` ×
        (source + ``moves`` moves) → close).
      rate: Poisson arrival rate, clients/second.
      facade, particles, chunk_size, mesh_box: the campaign every
        client runs (chunk_size only for facade="stream").
      priority_mix: (high, normal, low) lane probabilities.
      seed: the whole schedule's seed (arrivals, priorities,
        positions).
      collect_flux: return the final flux of the first k clients
        (``"parity"`` in the report) for a solo-replay bitwise gate.
      max_retries / retry_sleep: per-op refusal retry policy.
      timeout: per-client-thread join bound.
    """
    mix = np.asarray(priority_mix, dtype=np.float64)
    if mix.shape != (3,) or (mix < 0).any() or mix.sum() <= 0:
        raise ValueError(
            f"priority_mix must be 3 non-negative weights, got "
            f"{priority_mix!r}"
        )
    rng = np.random.default_rng(int(seed))
    gaps = rng.exponential(1.0 / float(rate), size=int(clients))
    arrivals = np.cumsum(gaps)
    priorities = rng.choice(_PRIORITIES, size=int(clients),
                            p=mix / mix.sum())
    results = [_ClientResult(str(p)) for p in priorities]
    t0 = time.perf_counter()
    threads = []
    for i in range(int(clients)):
        res = results[i]

        def body(i=i, res=res):
            try:
                _run_client(
                    host, port, i, res, t0 + float(arrivals[i]),
                    seed=int(seed), particles=int(particles),
                    batches=int(batches), moves=int(moves),
                    facade=str(facade), chunk_size=chunk_size,
                    mesh_box=mesh_box,
                    collect_flux=i < int(collect_flux),
                    max_retries=int(max_retries),
                    retry_sleep=float(retry_sleep),
                )
            except Exception as e:  # noqa: BLE001 — per-client
                # containment: one client's failure is a report row,
                # not a crashed run.
                res.error = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=body, daemon=True,
                             name=f"loadgen-c{i}")
        threads.append(t)
        t.start()
    for t in threads:
        t.join(timeout=float(timeout))
    wall = time.perf_counter() - t0
    alive = sum(1 for t in threads if t.is_alive())

    all_lat = [x for r in results for x in r.latencies]
    served = sum(r.moves_done for r in results)
    by_lane: Dict[str, List[int]] = {p: [] for p in _PRIORITIES}
    for r in results:
        by_lane[r.priority].append(r.moves_done)
    report: Dict[str, Any] = {
        "clients": int(clients),
        "clients_failed": sum(1 for r in results if r.error),
        "clients_timed_out": alive,
        "wall_s": wall,
        "served_moves": served,
        "moves_per_s": served / wall if wall > 0 else 0.0,
        "particle_moves_per_s":
            served * int(particles) / wall if wall > 0 else 0.0,
        "latency_ms": {
            "p50": (lambda q: None if q is None else q * 1e3)(
                _quantile(all_lat, 0.50)
            ),
            "p99": (lambda q: None if q is None else q * 1e3)(
                _quantile(all_lat, 0.99)
            ),
        },
        "lanes": {
            p: {
                "clients": len(by_lane[p]),
                "served_moves": sum(by_lane[p]),
                "jain": jain(by_lane[p]),
            }
            for p in _PRIORITIES
        },
        "refusals": {
            "busy_retries": sum(r.busy_retries for r in results),
            "overload_refusals":
                sum(r.overload_refusals for r in results),
        },
        "errors": [
            {"client": i, "error": r.error}
            for i, r in enumerate(results) if r.error
        ],
    }
    if collect_flux:
        report["parity"] = [
            {"client": i, "flux": results[i].flux}
            for i in range(min(int(collect_flux), int(clients)))
        ]
    return report


def format_report(report: Dict[str, Any]) -> str:
    """The human-readable summary the CLI prints (--json skips it)."""
    lat = report["latency_ms"]

    def ms(x):
        return "n/a" if x is None else f"{x:.2f}ms"

    lines = [
        f"clients          {report['clients']}"
        f" (failed {report['clients_failed']},"
        f" timed out {report['clients_timed_out']})",
        f"wall             {report['wall_s']:.2f}s",
        f"served moves     {report['served_moves']}"
        f" ({report['moves_per_s']:.1f} moves/s,"
        f" {report['particle_moves_per_s']:.0f} particle-moves/s)",
        f"latency          p50 {ms(lat['p50'])}  p99 {ms(lat['p99'])}",
        "refusals         "
        f"busy_retries={report['refusals']['busy_retries']} "
        f"overload={report['refusals']['overload_refusals']}",
    ]
    for p in _PRIORITIES:
        ln = report["lanes"][p]
        j = "n/a" if ln["jain"] is None else f"{ln['jain']:.3f}"
        lines.append(
            f"lane {p:<7}     clients={ln['clients']} "
            f"served={ln['served_moves']} jain={j}"
        )
    for e in report["errors"][:5]:
        lines.append(f"client {e['client']} FAILED: {e['error']}")
    if len(report["errors"]) > 5:
        lines.append(f"... and {len(report['errors']) - 5} more failures")
    return "\n".join(lines)
