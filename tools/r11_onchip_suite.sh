#!/bin/bash
# r11 on-chip suite (PR 11 — the round-12 cross-session batch fusion
# layer; suites are numbered by PR like r8-r10 before it, one less
# than the docs/DESIGN.md round they measure).
# Fired by a probe loop (tools/r5_probe_loop.sh pattern) the moment
# the TPU tunnel answers. ORDER MATTERS (r4 lesson): a QUICK headline
# bench first (a short window must still yield a fresh cached
# measurement), then the full bench (whose row set now includes the
# SERVICE_FUSION component row in-process), then THIS round's two
# measurements —
#   fusion_ab: fused vs unfused serving throughput at 1/4/8 sessions
#     at serving shape (pow2 per-session batches so slabs pack
#     pad-free; per-session bitwise flux-parity gate in BOTH arms and
#     the zero-compile measured-pass contract enforced inside the
#     tool). On-chip this decides the armed round-12 bet
#     (docs/PERF_NOTES.md "Cross-session batch fusion"): SHIP fusion
#     default-on if fused >= 1.15x unfused at 4+ sessions with
#     dispatches/move ~1/K; KILL (flip the default off) if < 1.05x —
#     on a real accelerator the dispatch amortization should GROW
#     relative to CPU (launch overhead is a bigger fraction when the
#     walk itself is fast), so a flat result means the pack/split
#     cost ate the win;
#   service_ab: the round-11 serving-tax re-measure (the ~30% CPU
#     figure fusion exists to shrink), unchanged shape so rounds
#     compare like-for-like —
# then the inherited subsystem A/Bs and engine experiments; chipless
# AOT compiles go last (the remote compile helper remains the prime
# wedge suspect).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r11_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r11 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_SCORING=0 PUMIUMTALLY_BENCH_RESILIENCE=0 PUMIUMTALLY_BENCH_SENTINEL=0 PUMIUMTALLY_BENCH_SERVICE=0 PUMIUMTALLY_BENCH_SERVICE_FUSION=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-12 measurement: cross-session fusion at serving shape —
# larger per-session batches than the in-bench row (still pow2 so
# equal sessions pack pad-free) plus a 16-session point, because on
# chip the dispatch amortization is the whole question. Decides the
# ship/kill rule in the header.
run fusion_ab 1800 env PUMIUMTALLY_AB_N=32768 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 PUMIUMTALLY_AB_SESSIONS=1,4,8,16 PUMIUMTALLY_AB_TRIALS=3 python tools/exp_fusion_ab.py
# The round-11 serving-tax re-measure (the number fusion shrinks),
# full shape, unchanged so rounds compare like-for-like.
run service_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=4 PUMIUMTALLY_AB_BATCHES=10 python tools/exp_service_ab.py
# Inherited subsystem A/Bs (r7-r10 lineage), unchanged shapes so
# rounds compare like-for-like.
run scoring_ab  1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_MOVES=6 python tools/exp_scoring_ab.py
run sentinel_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_sentinel_ab.py
run resilience_ab 1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_resilience_ab.py
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects).
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
