"""Breakdown of a realistic bench move: device step vs host staging."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.api.tally import _move_step

N, DIV, MEAN_STEP = 500_000, 20, 0.25
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
cfg = TallyConfig(check_found_all=False)
t = PumiTally(mesh, N, cfg)
rng = np.random.default_rng(0)
pos = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(pos.reshape(-1).copy())

def next_dest(p):
    return np.clip(p + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)

# one full API move (compile)
d = next_dest(pos)
t.MoveToNextLocation(pos.reshape(-1).copy(), d.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
pos = t.positions.astype(np.float64)

# device-only: jitted move_step with on-device arrays, origins = committed x
x, elem, flux = t.x, t.elem, t.flux
dts = []
for _ in range(6):
    d = jnp.asarray(next_dest(np.asarray(x, np.float64)), x.dtype)
    fly = jnp.ones((N,), jnp.int8); w = jnp.ones((N,), x.dtype)
    jax.block_until_ready((d, x))
    t0 = time.perf_counter()
    x, elem, flux, ok = _move_step(mesh, x, elem, x, d, fly, w, flux,
                                   tol=t._tol, max_iters=t._max_iters)
    jax.block_until_ready(flux)
    dts.append(time.perf_counter() - t0)
print("device-only move_step ms:", [f"{x*1e3:.0f}" for x in dts])

# full API move timing
dts2 = []
for _ in range(4):
    d = next_dest(pos)
    t0 = time.perf_counter()
    t.MoveToNextLocation(pos.reshape(-1).copy(), d.reshape(-1).copy(),
                         np.ones(N, np.int8), np.ones(N))
    dts2.append(time.perf_counter() - t0)
    pos = t.positions.astype(np.float64)
print("full API move ms     :", [f"{x*1e3:.0f}" for x in dts2])
