"""How much of the API move is host->device transfer over the tunnel?"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

N = 500_000
rng = np.random.default_rng(0)
a64 = rng.uniform(size=(N, 3))

def t(f, n=5):
    f(); t0 = time.perf_counter()
    for _ in range(n): out = f()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n

print(f"host f64->f32 convert : {t(lambda: a64.astype(np.float32))*1e3:7.1f} ms")
a32 = a64.astype(np.float32)
print(f"device_put 6MB f32    : {t(lambda: jax.device_put(a32))*1e3:7.1f} ms")
print(f"device_put 12MB f64   : {t(lambda: jax.device_put(a64))*1e3:7.1f} ms")
x = jax.device_put(a32)
print(f"device->host 6MB      : {t(lambda: np.asarray(x))*1e3:7.1f} ms")
