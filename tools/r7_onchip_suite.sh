#!/bin/bash
# Round-7 on-chip suite: fired by a probe loop (tools/r5_probe_loop.sh
# pattern) the moment the TPU tunnel answers. ORDER MATTERS (r4
# lesson): a QUICK headline bench first (a short window must still
# yield a fresh cached measurement), then the full bench (whose row
# set now includes the batch_stats component row in-process), then
# THIS round's measurement — the batch-statistics overhead + trigger
# convergence A/B at full bench scale — then the inherited engine
# experiments; chipless AOT compiles go last (the remote compile
# helper remains the prime wedge suspect).
#
# Crash-safety: stage logs stream DIRECTLY into the repo dir, the
# digest regenerates before AND after every stage, and its write is
# atomic (tmp + mv) so a kill mid-write cannot destroy the last good
# one.
set -u
RD=/root/repo/tools/r7_onchip
mkdir -p "$RD"
cd /root/repo
echo "suite started $(date)" > "$RD/status"
STAGES=""
write_digest() {
  local DG="$RD/digest.md"
  {
    echo "# r7 on-chip suite digest"
    cat "$RD/status"
    echo
    for f in $STAGES; do
      echo "## $f"
      grep -E '"metric"|"row"|moves/s|OK|FAILED|FATAL|FAILURE|rc=' "$RD/$f.log" 2>/dev/null | tail -20
      echo
    done
  } > "$DG.tmp" 2>/dev/null && mv "$DG.tmp" "$DG"
}
run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  STAGES="$STAGES $name"
  echo "$name started $(date)" >> "$RD/status"
  write_digest
  timeout "$tmo" "$@" > "$RD/$name.log" 2>&1
  local rc=$?
  echo "$name done $(date) rc=$rc" >> "$RD/status"
  write_digest
}
# Quick headline FIRST (~6 min): if the window closes mid-suite, a
# fresh on-chip measurement is already cached (record_success).
run bench_quick 900 env PUMIUMTALLY_BENCH_AUTOTUNE=0 PUMIUMTALLY_BENCH_VMEM=0 PUMIUMTALLY_BENCH_GATHER_BLOCKED=0 PUMIUMTALLY_BENCH_PINCELL_TUNED=0 PUMIUMTALLY_BENCH_CPU_BASELINE=0 PUMIUMTALLY_BENCH_TABLE_PRECISION=0 PUMIUMTALLY_BENCH_BATCH_STATS=0 PUMIUMTALLY_BENCH_MAX_WAIT=120 python bench.py
run bench_clean 2700 python bench.py
# THE round-7 measurement: the batch-statistics subsystem at the FULL
# headline shape (500k particles, 48k tets; the in-bench row runs
# 100k to bound its budget) — close-batch overhead vs the stats-off
# arm (flux parity asserted bitwise inside the tool), fenced per-close
# lane-update/trigger costs (the trigger's single scalar D2H is the
# sync), and the trigger convergence trace (monotone RE decay,
# threshold fire point, 1/sqrt(N) batches-remaining projection) —
# captured non-interactively as one JSON line.
run stats_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_BATCHES=12 python tools/exp_stats_ab.py
# Inherited engine experiments (r5/r6 lineage), unchanged shapes so
# rounds compare like-for-like.
run table_ab    1800 env PUMIUMTALLY_AB_N=500000 PUMIUMTALLY_AB_TRIALS=5 python tools/exp_table_precision_ab.py
run blocked     3300 python tools/exp_r5_blocked.py 500000 4
run frontier_ab 1800 python tools/exp_frontier_ab.py
run native      1500 bash -c 'python -m pumiumtally_tpu.cli box --nx 20 --ny 20 --nz 20 /tmp/bench48k.osh && make -C native bench_host && PYTHONPATH=/root/repo ./native/bench_host /tmp/bench48k.osh 500000 6'
# Chipless-certified compiles go last (wedge suspects).
run vmem_prod   1800 python tools/exp_r4_vmem_compile.py 500000
echo "suite finished $(date)" >> "$RD/status"
write_digest
