"""Can device_put overlap with device compute on this backend?"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

N = 500_000
rng = np.random.default_rng(0)
bufs = [rng.uniform(size=(N, 3)).astype(np.float32) for _ in range(4)]

@jax.jit
def burn(x, iters=200):
    def body(i, s):
        return s @ jnp.eye(3, dtype=s.dtype) * 0.999 + 1e-6
    return jax.lax.fori_loop(0, iters, body, x)

x0 = jax.device_put(bufs[0]); jax.block_until_ready(x0)
r = burn(x0); jax.block_until_ready(r)

# compute alone
t0 = time.perf_counter(); r = burn(x0); jax.block_until_ready(r)
t_compute = time.perf_counter() - t0
# transfer alone
t0 = time.perf_counter(); y = jax.device_put(bufs[1]); jax.block_until_ready(y)
t_xfer = time.perf_counter() - t0
# interleaved: start compute, then transfer while it runs
t0 = time.perf_counter()
r = burn(x0)                      # async dispatch
z = jax.device_put(bufs[2])       # transfer during compute?
jax.block_until_ready((r, z))
t_both = time.perf_counter() - t0
print(f"compute={t_compute*1e3:.0f}ms xfer={t_xfer*1e3:.0f}ms "
      f"interleaved={t_both*1e3:.0f}ms (sum={1e3*(t_compute+t_xfer):.0f})")
