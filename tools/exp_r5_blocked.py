"""Gather-blocked engine A/B on the bench box and the 1M-tet lattice.

The r5 headline bet (walk_block_kernel="gather", docs/PERF_NOTES.md):
per-block tables stay on-chip, reproducing the measured small-table
gather regime (2.2-2.4M moves/s at L<=3k vs ~1.1M monolithic on the
48k-tet box). This experiment measures, on whatever backend is
attached:

  - monolithic continue-mode rate (the r4 headline protocol);
  - gather-blocked continue-mode rate at a few block-size bounds;
  - the same pair on the ~1M-tet assembly lattice (BASELINE config 2),
    where the monolithic walk table (~86 MB) dwarfs VMEM and blocking
    is the only way any table locality exists at all.

Usage: python tools/exp_r5_blocked.py [n_particles] [moves]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    TallyConfig,
    build_box,
)

MEAN_STEP = 0.25


def drive(t, pts, moves) -> float:
    """bench.timed_moves-shaped scaffold (warmup move, scalar-fetch
    sync, conservation over ALL moves) — NOT bench.timed_moves itself:
    that one sys.exit(1)s on a conservation miss, while this experiment
    must contain a single row's failure and keep sweeping the scarce
    chip window (AssertionError is caught per row in run_mesh)."""
    n = pts[0].shape[0]
    t.CopyInitialPosition(pts[0].reshape(-1).copy())
    t.MoveToNextLocation(None, pts[1].reshape(-1).copy())  # warmup/compile
    float(jnp.sum(t.flux))
    t0 = time.perf_counter()
    for m in range(2, moves + 2):
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())
    total = float(np.float64(jnp.sum(t.flux)))
    dt = time.perf_counter() - t0
    expect = sum(
        float(np.linalg.norm(pts[m] - pts[m - 1], axis=1).sum())
        for m in range(1, moves + 2)
    )
    rel = abs(total - expect) / expect
    assert rel < 1e-6, f"conservation off: {rel:.2e}"
    return n * moves / dt


def run_mesh(label, mesh, n, moves, bounds, capf=2.0) -> None:
    from pumiumtally_tpu.utils.autotune import _workload

    # The shared bbox-scaled bench-shaped trajectory (one generator for
    # bench/autotune/experiments); f64 on the host so the conservation
    # expectation is exact in the accumulation dtype.
    pts = [np.asarray(p, np.float64)
           for p in _workload(mesh, n, moves, MEAN_STEP, 0)]
    try:
        t = PumiTally(mesh, n, TallyConfig(check_found_all=False,
                                           fenced_timing=False))
        r = drive(t, pts, moves)
        print(f"{label} monolithic: {r / 1e6:.2f}M moves/s", flush=True)
        del t
    except Exception as e:  # noqa: BLE001 — baseline must not cost the sweep
        print(f"{label} monolithic FAILED: "
              f"{type(e).__name__}: {str(e)[:500]}", flush=True)
    for bound in bounds:
        t = None
        try:
            t = PartitionedPumiTally(
                mesh, n,
                TallyConfig(capacity_factor=capf,
                            walk_vmem_max_elems=bound,
                            walk_block_kernel="gather",
                            check_found_all=False, fenced_timing=False),
            )
            r = drive(t, pts, moves)
            print(f"{label} gather-blocked L<={bound} "
                  f"({t.engine.blocks_per_chip} blocks, "
                  f"L={t.engine.part.L}, "
                  f"rounds={t.engine.last_walk_rounds}): "
                  f"{r / 1e6:.2f}M moves/s", flush=True)
        except Exception as e:  # noqa: BLE001 — report and continue sweep
            print(f"{label} gather-blocked L<={bound} FAILED: "
                  f"{type(e).__name__}: {str(e)[:500]}", flush=True)
        del t


def main(n: int, moves: int) -> None:
    print(f"backend={jax.default_backend()} n={n} moves={moves}", flush=True)
    mesh48 = build_box(1, 1, 1, 20, 20, 20, dtype=jnp.float32)
    run_mesh("box48k", mesh48, n, moves, bounds=(3072, 6144))
    del mesh48

    # The flagship pincell geometry (~22k anisotropic tets, the same
    # FLAGSHIP_PINCELL mesh bench.py measures): if the gather sub-split
    # wins here too, the BASELINE configs[0] workload gets the same
    # lift as the box.
    from pumiumtally_tpu.mesh.pincell import FLAGSHIP_PINCELL, build_pincell

    pmesh, _ = build_pincell(**FLAGSHIP_PINCELL)
    run_mesh("pincell22k", pmesh, n, moves, bounds=(3072,))
    del pmesh

    from pumiumtally_tpu.mesh.pincell import build_lattice

    t0 = time.perf_counter()
    mesh1m, _, _ = build_lattice(10, 10, n_theta=24, n_rings_fuel=4,
                                 n_rings_pad=4, nz=10, dtype=jnp.float32)
    print(f"lattice {mesh1m.nelems} tets built in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)
    # capf 4.0: ~350 spatial blocks at n/350 mean occupancy need real
    # headroom against Poisson + migration-arrival fluctuations (the
    # 2.0 default overflowed at small n). The 12288 bound probes the
    # fewer-blocks/fewer-rounds corner (L<=3072 needed ~45 migration
    # rounds on the lattice — block size must scale with step length).
    run_mesh("lattice1M", mesh1m, n, moves, bounds=(3072, 12288),
             capf=4.0)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 500_000,
         int(sys.argv[2]) if len(sys.argv) > 2 else 4)
