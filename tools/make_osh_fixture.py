"""Generate the checked-in ``tests/data/*.osh`` fixtures.

These fixtures validate ``pumiumtally_tpu.io.osh`` against an
INDEPENDENT implementation of the Omega_h stream layout (reference
PumiTallyImpl.cpp:562 reads real ``msh2osh`` output): every byte here
is written by fresh ``struct.pack`` code sharing nothing with
``io/osh.py``, and the mesh-derivation conventions deliberately differ
from that module's writer in the ways genuine Omega_h differs:

- entities (edges/triangles) are numbered by FIRST APPEARANCE while
  iterating parents in order — Omega_h's ``reflect_down`` derivation —
  not by sorted-unique key;
- a triangle/edge stores its vertices in the order induced by the
  FIRST parent that defined it, not ascending — so the tet→tri and
  tri→edge alignment codes are nontrivial (rotations and flips appear,
  computed per ``Omega_h_align.hpp``: ``code = rotation << 1 | flip``),
  exercising the reader's claim that its vertex-set composition is
  insensitive to them;
- streams carry the tag set ``msh2osh`` output carries (``class_id`` /
  ``class_dim`` on every dimension, ``global`` ids) and RIB hints are
  present in the single-part stream;
- the 2-part fixture has realistically SHARED interface vertices with
  owner arrays pointing at the lower rank (not the fully-owned layout
  io/osh.py's writer emits).

What this cannot prove: agreement with bytes produced by a genuine
Omega_h build (none is obtainable in this environment — no network).
It does prove the reader decodes a stream written from the documented
layout by code that cannot share a systematic bug with it.

NOTE (round 4): these fixtures deliberately keep the BIG-endian,
version-in-stream framing this repo's earlier layout reading used.
The reader now auto-detects framing variants (io/osh.py
``_read_stream_any``), the package writer emits the upstream-protocol
variant (little-endian, version in the directory file only), and
``native/osh_writer.cpp`` — a C++ transcription of the upstream
writer — generates fixtures in THAT framing; keeping this generator's
framing unchanged preserves test coverage of the transposed variant.

Run from the repo root:  python tools/make_osh_fixture.py
"""

from __future__ import annotations

import os
import struct
import sys
import zlib

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "data")

MAGIC = b"\xa1\x1a"
VERSION = 9

# Omega_h_simplex.hpp templates (same constants the reader documents).
TET_FACES = [[0, 2, 1], [0, 1, 3], [1, 2, 3], [2, 0, 3]]
TRI_EDGES = [[0, 1], [1, 2], [2, 0]]

# The unit cube split into 6 tets around the main diagonal v0-v6.
CUBE_COORDS = np.array([
    [0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
    [0, 0, 1], [1, 0, 1], [1, 1, 1], [0, 1, 1],
], dtype=np.float64)
CUBE_TETS = np.array([
    [0, 1, 2, 6], [0, 2, 3, 6], [0, 3, 7, 6],
    [0, 7, 4, 6], [0, 4, 5, 6], [0, 5, 1, 6],
], dtype=np.int64)


def wv(f, fmt, v):
    f.write(struct.pack(">" + fmt, v))


def warr(f, arr, dtype):
    a = np.ascontiguousarray(arr, dtype=dtype)
    wv(f, "i", a.size)
    z = zlib.compress(a.tobytes(), 6)
    wv(f, "q", len(z))
    f.write(z)


def wstr(f, s):
    b = s.encode()
    wv(f, "i", len(b))
    f.write(b)


def align_code(stored, wanted):
    """Omega_h_align.hpp: code = (rotation << 1) | is_flipped, for the
    transformation taking the stored vertex tuple onto the parent's
    template-induced tuple."""
    stored = list(stored)
    n = len(stored)
    for flip in (0, 1):
        t = stored if not flip else (
            [stored[0]] + stored[1:][::-1] if n == 3 else stored[::-1]
        )
        for rot in range(n):
            if t[rot:] + t[:rot] == list(wanted):
                return (rot << 1) | flip
    raise AssertionError(f"no alignment maps {stored} onto {wanted}")


def derive_down(parents, templates):
    """First-appearance child numbering; stored child vertex order from
    the first defining parent (Omega_h reflect_down convention).
    Returns (child_verts [C,k], parent2child [P,t], codes [P*t])."""
    child_of = {}
    child_verts = []
    p2c = np.zeros((len(parents), len(templates)), np.int64)
    codes = np.zeros((len(parents), len(templates)), np.int8)
    for p, pv in enumerate(parents):
        for t, tmpl in enumerate(templates):
            induced = [int(pv[i]) for i in tmpl]
            key = tuple(sorted(induced))
            if key not in child_of:
                child_of[key] = len(child_verts)
                child_verts.append(induced)  # stored = creator's order
            c = child_of[key]
            p2c[p, t] = c
            codes[p, t] = align_code(child_verts[c], induced)
    return np.array(child_verts, np.int64), p2c, codes.reshape(-1)


def write_stream(path, coords, tets, comm_size=1, comm_rank=0,
                 vert_global=None, elem_global=None, owners=None,
                 hints=False):
    tri_verts, tet2tri, tet_codes = derive_down(tets, TET_FACES)
    edge_verts, tri2edge, tri_codes = derive_down(tri_verts, TRI_EDGES)
    nv, ned, ntr, nte = (coords.shape[0], edge_verts.shape[0],
                         tri_verts.shape[0], tets.shape[0])
    nents = [nv, ned, ntr, nte]
    with open(path, "wb") as f:
        f.write(MAGIC)
        wv(f, "i", VERSION)
        wv(f, "b", 1)          # compressed
        wv(f, "b", 0)          # family: simplex
        wv(f, "b", 3)          # dim
        wv(f, "i", comm_size)
        wv(f, "i", comm_rank)
        wv(f, "b", 2)          # parting: elem-based
        wv(f, "i", 0)          # nghost_layers
        if hints:
            wv(f, "b", 1)
            wv(f, "i", 2)      # naxes
            f.write(struct.pack(">6d", *([0.5] * 6)))  # 2 axes x 3 x f64
        else:
            wv(f, "b", 0)
        wv(f, "i", nv)
        warr(f, edge_verts.reshape(-1), ">i4")
        warr(f, tri2edge.reshape(-1), ">i4")
        warr(f, tri_codes, ">i1")
        warr(f, tet2tri.reshape(-1), ">i4")
        warr(f, tet_codes, ">i1")
        for d in range(4):
            tags = []
            if d == 0:
                tags.append(("coordinates", 3, 5, coords.reshape(-1), ">f8"))
                if vert_global is not None:
                    tags.append(("global", 1, 3, vert_global, ">i8"))
            if d == 3 and elem_global is not None:
                tags.append(("global", 1, 3, elem_global, ">i8"))
            # the classification tags msh2osh output carries
            tags.append(("class_id", 1, 2,
                         np.full(nents[d], 73, np.int64), ">i4"))
            tags.append(("class_dim", 1, 0,
                         np.full(nents[d], 3, np.int64), ">i1"))
            wv(f, "i", len(tags))
            for name, ncomps, typ, data, dt in tags:
                wstr(f, name)
                wv(f, "b", ncomps)
                wv(f, "b", typ)
                warr(f, data, dt)
            if comm_size > 1:
                ranks, idxs = owners[d]
                warr(f, ranks, ">i4")
                warr(f, idxs, ">i4")


def main():
    os.makedirs(OUT, exist_ok=True)

    # -- single-part fixture ------------------------------------------
    d1 = os.path.join(OUT, "cube_omega1.osh")
    os.makedirs(d1, exist_ok=True)
    with open(os.path.join(d1, "nparts"), "w") as f:
        f.write("1\n")
    with open(os.path.join(d1, "version"), "w") as f:
        f.write(f"{VERSION}\n")
    write_stream(os.path.join(d1, "0.osh"), CUBE_COORDS, CUBE_TETS,
                 vert_global=np.arange(8), elem_global=np.arange(6),
                 hints=True)

    # -- two-part fixture (shared interface vertices, real owners) ----
    d2 = os.path.join(OUT, "cube_omega2.osh")
    os.makedirs(d2, exist_ok=True)
    with open(os.path.join(d2, "nparts"), "w") as f:
        f.write("2\n")
    with open(os.path.join(d2, "version"), "w") as f:
        f.write(f"{VERSION}\n")
    split = [CUBE_TETS[:3], CUBE_TETS[3:]]
    rank_gverts = []
    rank_local = []
    for rtets in split:
        gv, inv = np.unique(rtets, return_inverse=True)  # local numbering
        rank_gverts.append(gv)
        rank_local.append(inv.reshape(rtets.shape))
    for rank in range(2):
        gv = rank_gverts[rank]
        # owners: a shared vertex belongs to the LOWER rank that stores
        # it; idx = its local id on the owner rank.
        ranks = np.zeros(gv.size, np.int64)
        idxs = np.zeros(gv.size, np.int64)
        other = rank_gverts[0]
        for i, g in enumerate(gv):
            if rank == 1 and g in other:
                ranks[i] = 0
                idxs[i] = int(np.searchsorted(other, g))
            else:
                ranks[i] = rank
                idxs[i] = i
        nloc_e = split[rank].shape[0]
        tri_verts = derive_down(rank_local[rank], TET_FACES)[0]
        nloc_t = tri_verts.shape[0]
        nloc_ed = derive_down(tri_verts, TRI_EDGES)[0].shape[0]
        owners = {
            0: (ranks, idxs),
            1: (np.full(nloc_ed, rank), np.arange(nloc_ed)),
            2: (np.full(nloc_t, rank), np.arange(nloc_t)),
            3: (np.full(nloc_e, rank), np.arange(nloc_e)),
        }
        write_stream(
            os.path.join(d2, f"{rank}.osh"),
            CUBE_COORDS[gv], rank_local[rank],
            comm_size=2, comm_rank=rank,
            vert_global=gv.astype(np.int64),
            elem_global=np.arange(3 * rank, 3 * rank + 3, dtype=np.int64),
            owners=owners,
        )
    print(f"wrote {d1} and {d2}")


if __name__ == "__main__":
    sys.exit(main())
