"""Summarize the r3 on-chip suite logs into a PERF_NOTES-ready digest.

The detached recovery loop (/tmp/r3_probe_loop.sh) runs the suite once
when the TPU tunnel answers and mirrors logs into tools/r4_onchip/.
This script condenses them: cascade sweep table, VMEM-prototype
win/kill per mesh size, protocol A/B rates, locate A/B, the native
bench_host row, and the final bench JSON — so whoever picks up the
logs (this session, the round driver's auto-commit, or the next
session) gets the numbers without re-reading raw logs.

Usage: python tools/analyze_r3_onchip.py [logdir]   (default: tools/r4_onchip)
"""

from __future__ import annotations

import json
import os
import re
import sys


def section(title: str) -> None:
    print(f"\n## {title}")


def show_matching(path: str, patterns, max_lines=40) -> bool:
    if not os.path.exists(path):
        print(f"(missing: {os.path.basename(path)})")
        return False
    shown = 0
    rx = re.compile("|".join(patterns))
    with open(path, errors="replace") as f:
        for line in f:
            if rx.search(line):
                print(line.rstrip())
                shown += 1
                if shown >= max_lines:
                    print("... (truncated)")
                    break
    if not shown:
        print(f"(no matching lines in {os.path.basename(path)} — "
              "tail follows)")
        with open(path, errors="replace") as f:
            for line in f.readlines()[-10:]:
                print(" ", line.rstrip())
    return shown > 0


def main() -> None:
    # Anchored to this file, so the default works from any cwd.
    d = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "r4_onchip",
    )
    status = os.path.join(d, "status")
    if not os.path.exists(status):
        print(f"no suite run found under {d!r} (status file missing)")
        return
    print("# on-chip suite digest")
    with open(status) as f:
        print(f.read().strip())

    section("cascade sweep (perm_mode x window_factor x cond_every)")
    show_matching(os.path.join(d, "cascade.log"),
                  [r"perm=", r"^best:"])
    section("VMEM one-hot/pallas prototype (win or kill per L)")
    show_matching(os.path.join(d, "vmem.log"),
                  [r"^L=", r"walk_gather", r"onehot", r"pallas", r"FAILED"])
    section("PRODUCTION vmem walk (ops/vmem_walk.py): compile/parity/rates")
    show_matching(os.path.join(d, "vmem_prod.log"),
                  [r"COMPILE", r"PARITY", r"^L=", r"ENGINE", r"FAILED"])
    section("scale rows (BASELINE config 2: ~1M-tet lattice)")
    show_matching(os.path.join(d, "scale.log"),
                  [r"box48k", r"lattice", r"built", r"backend"])
    section("API protocol A/B (two_phase / forced / continue)")
    show_matching(os.path.join(d, "api_ab.log"),
                  [r"moves/s", r"two_phase", r"continue", r"rate"])
    section("locate vs walk localization")
    show_matching(os.path.join(d, "locate_ab.log"),
                  [r"locate", r"walk", r"ms", r"x\b"])
    section("component profile")
    show_matching(os.path.join(d, "profile.log"),
                  [r"ms", r"gather", r"scatter", r"perm", r"argsort"])
    section("native C-ABI host")
    show_matching(os.path.join(d, "native.log"),
                  [r"native_two_phase_moves_per_sec", r"error", r"FAIL"])
    def bench_json(path: str) -> None:
        if not os.path.exists(path):
            print(f"(missing: {os.path.basename(path)})")
            return
        found = False
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        j = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    found = True
                    for k in ("value", "vs_baseline", "stale",
                              "two_phase_moves_per_sec",
                              "continue_moves_per_sec",
                              "autotuned_knobs", "link_mb_per_sec",
                              "vmem_blocked",
                              "conservation_rel_err"):
                        if k in j:
                            print(f"  {k}: {j[k]}")
        if not found:
            show_matching(path, [r"FATAL", r"probe", r"#"])

    section("bench.py JSON")
    bench_json(os.path.join(d, "bench.log"))

    # Second-window suite (tools/r4b_onchip_suite.sh) artifacts, if it
    # ever fired: clean bench re-run, native re-run, production-vmem
    # compile+rates with the layout-law fixes in.
    if os.path.exists(os.path.join(d, "r4b_status")):
        section("SECOND WINDOW (r4b): status")
        with open(os.path.join(d, "r4b_status")) as f:
            print(f.read().strip())
        section("r4b clean bench JSON")
        bench_json(os.path.join(d, "r4b_bench_clean.log"))
        section("r4b production vmem compile/rates")
        show_matching(os.path.join(d, "r4b_vmem_prod.log"),
                      [r"COMPILE", r"PARITY", r"^L=", r"ENGINE", r"FAILED"])
        section("r4b native C-ABI host")
        show_matching(os.path.join(d, "r4b_native.log"),
                      [r"native_two_phase_moves_per_sec", r"error",
                       r"FAIL"])


if __name__ == "__main__":
    main()
