"""VMEM-resident walk prototype for small partitions (round-3 item 5).

The production walk's floor is the HBM row gather (~80 B/crossing at
the measured ~4-5 GB/s row-granularity DMA — docs/PERF_NOTES.md). For
a PARTITION of L <= ~4k tets the packed [L,32] table (~0.5 MB) fits
VMEM (~16 MB/core on v5e), so the gather can become a one-hot MXU
matmul executed entirely on-chip:

    row[W,32]  = onehot(elem)[W,L] @ table[L,32]      (row fetch)
    flux[L]   += contrib[1,W] @ onehot(elem)[W,L]     (tally scatter)

Two implementations, bitwise-checked against ops.walk.walk:

- ``walk_onehot_jnp``: the same lock-step loop in plain jnp with the
  one-hot matmuls. XLA may or may not fuse the [W,L] one-hot into the
  dot; if it materializes in HBM this LOSES to the gather (4·L bytes
  vs 80 per crossing) — measuring that is part of the experiment.
- ``walk_vmem_pallas``: ONE pallas kernel per particle tile: the
  table is pinned in VMEM, the whole while-loop runs inside the
  kernel (no per-iteration XLA op boundaries, no HBM round-trips for
  the carries), the one-hot lives only in VMEM scratch, and the tile's
  flux partial accumulates in VMEM and is written once.

Cost model (why only small L can win): the MXU work is
2·W·L·128 FLOPs per iteration per tile regardless of how many lanes
are still active, i.e. ~2·L·128/f FLOPs per crossing at active
fraction f. At L=512 and f~0.5 that is ~6-10 ns/crossing on a v5e
MXU — ~3-5x under the measured gather path; at L=4096 it is a wash.
The partitioned engine hands each chip E/ndev elements, so this is a
win exactly when partitions are (or are sub-split to) a few thousand
tets — the sub-splitting pause/migrate overhead is NOT modeled here
and must come off the top of any measured win.

Usage:
  python tools/exp_r3_vmem.py check     # CPU: semantics vs walk()
  python tools/exp_r3_vmem.py bench [N] # TPU: rate sweep over L, W
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.walk import walk

W_TILE = 256  # particles per pallas tile / jnp chunk


def _padded_table(mesh):
    """[L,32] f32: 12 normals, 4 offsets, 4 adjacency ids, 12 zeros.

    Pure jnp (no numpy round-trip): under jit the mesh arrays are
    tracers — the r4 on-chip run died here with
    TracerArrayConversionError before any prototype number landed."""
    t = jnp.asarray(mesh.walk_table, jnp.float32)
    L, c = t.shape
    return jnp.concatenate([t, jnp.zeros((L, 32 - c), jnp.float32)], axis=1)


def _advance_cols(row, s, elem, dest, d0, eff_w, done, tol, one):
    """The walk's per-iteration math from a fetched [W,32] row, written
    column-wise (no [W,4,3] reshape — pallas/Mosaic friendly). Mirrors
    ops/walk.py::advance exactly; bitwise-identical given equal rows."""
    active = ~done
    # a_f = n_f . d0, b_f = off_f - n_f . x0   (x0 = dest - d0)
    a_list, b_list = [], []
    for f in range(4):
        nx, ny, nz = row[:, 3 * f], row[:, 3 * f + 1], row[:, 3 * f + 2]
        a_f = nx * d0[:, 0] + ny * d0[:, 1] + nz * d0[:, 2]
        ndest = nx * dest[:, 0] + ny * dest[:, 1] + nz * dest[:, 2]
        b_f = row[:, 12 + f] - ndest + a_f
        a_list.append(a_f)
        b_list.append(b_f)
    inf = jnp.asarray(jnp.inf, s.dtype)
    s_fs = []
    for f in range(4):
        crossing = a_list[f] * (one - s) > tol
        s_f = jnp.where(crossing, b_list[f] / jnp.where(crossing, a_list[f], one), inf)
        s_fs.append(jnp.maximum(s_f, s))
    # min + argmin over the 4 faces, unrolled
    s_exit = jnp.minimum(jnp.minimum(s_fs[0], s_fs[1]),
                         jnp.minimum(s_fs[2], s_fs[3]))
    adj = [row[:, 16 + f].astype(jnp.int32) for f in range(4)]
    next_elem = adj[3]
    for f in (2, 1, 0):  # first minimal face wins (matches argmin)
        next_elem = jnp.where(s_fs[f] == s_exit, adj[f], next_elem)
    reached = s_exit >= one
    s_new = jnp.where(reached, one, s_exit)
    hit_boundary = (~reached) & (next_elem == -1)
    contrib = jnp.where(active, (s_new - s) * eff_w, 0.0)
    moving = active & ~reached & ~hit_boundary
    elem = jnp.where(moving, next_elem, elem)
    s = jnp.where(active, s_new, s)
    done = done | reached | hit_boundary
    return s, elem, done, contrib


def walk_onehot_jnp(mesh, x, elem, dest, in_flight, weight, flux, *,
                    tol, max_iters):
    """Lock-step walk with one-hot-MXU row fetch + flux accumulation
    (no compaction cascade; per-chunk loop keeps the one-hot at
    [W_TILE, L])."""
    L = mesh.nelems
    table = _padded_table(mesh)
    one = jnp.asarray(1.0, x.dtype)
    n = x.shape[0]
    pad = (-n) % W_TILE
    def padv(a, fill):
        return jnp.concatenate([a, jnp.full((pad, *a.shape[1:]), fill, a.dtype)])
    if pad:
        x, dest = padv(x, 0.0), padv(dest, 0.0)
        elem = padv(elem, 0)
        in_flight = padv(in_flight, 0)
        weight = padv(weight, 0.0)
    d0 = dest - x
    seg = jnp.linalg.norm(d0, axis=1)
    eff_w = jnp.where(in_flight.astype(bool), weight * seg, 0.0)
    done0 = in_flight != in_flight
    # hold particles (dest == x) finish on iteration 1 like walk()
    T = (n + pad) // W_TILE
    shp = lambda a: a.reshape(T, W_TILE, *a.shape[1:])  # noqa: E731
    s0 = jnp.zeros_like(seg)

    def chunk(args):
        s, elem, done, dest_c, d0_c, effw_c = args
        iota = jnp.arange(L, dtype=jnp.int32)

        def body(carry):
            it, s, elem, done, fl = carry
            oh = (elem[:, None] == iota[None, :]).astype(table.dtype)
            row = oh @ table  # [W,32]
            s, elem, done, contrib = _advance_cols(
                row, s, elem, dest_c, d0_c, effw_c, done, tol, one
            )
            fl = fl + contrib[None, :] @ oh  # [1,L]
            return it + 1, s, elem, done, fl

        def cond(carry):
            it, _, _, done, _ = carry
            return (it < max_iters) & jnp.any(~done)

        it, s, elem, done, fl = lax.while_loop(
            cond, body,
            (jnp.asarray(0, jnp.int32), s, elem, done,
             jnp.zeros((1, L), x.dtype)),
        )
        return s, elem, done, fl[0]

    s, elem, done, fparts = lax.map(
        chunk,
        (shp(s0), shp(elem), shp(done0), shp(dest), shp(d0), shp(eff_w)),
    )
    s, elem, done = s.reshape(-1)[:n], elem.reshape(-1)[:n], done.reshape(-1)[:n]
    dest, d0 = dest[:n], d0[:n]
    flux = flux + jnp.sum(fparts, axis=0)
    exited = done & (s < one)
    x_fin = jnp.where((done & ~exited)[:, None], dest,
                      dest + (s - one)[:, None] * d0)
    return x_fin, elem, done, exited, flux


def walk_vmem_pallas(mesh, x, elem, dest, in_flight, weight, flux, *,
                     tol, max_iters, interpret=False):
    """One pallas kernel per W_TILE particles: table in VMEM, the whole
    while-loop inside the kernel, flux partial in VMEM scratch."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L = mesh.nelems
    table = _padded_table(mesh)
    fdtype = x.dtype
    one = jnp.asarray(1.0, fdtype)
    n = x.shape[0]
    pad = (-n) % W_TILE
    def padv(a, fill):
        return jnp.concatenate([a, jnp.full((pad, *a.shape[1:]), fill, a.dtype)])
    if pad:
        x, dest = padv(x, 0.0), padv(dest, 0.0)
        elem = padv(elem, 0)
        in_flight = padv(in_flight, 0)
        weight = padv(weight, 0.0)
    d0 = dest - x
    seg = jnp.linalg.norm(d0, axis=1)
    eff_w = jnp.where(in_flight.astype(bool), weight * seg, 0.0)
    done0 = (in_flight == in_flight) & False
    T = (n + pad) // W_TILE
    max_iters = int(max_iters)  # static inside the kernel

    def kernel(table_ref, s_ref, elem_ref, done_ref, dest_ref, d0_ref,
               effw_ref, s_out, elem_out, done_out, flux_out, fl_scr):
        table_v = table_ref[:]
        dest_c = dest_ref[:]
        d0_c = d0_ref[:]
        effw_c = effw_ref[:]
        one_k = jnp.asarray(1.0, s_ref.dtype)  # kernel-local constant
        iota = lax.broadcasted_iota(jnp.int32, (W_TILE, L), 1)

        def body(carry):
            it, s, elem, done, fl = carry
            oh = (elem[:, None] == iota).astype(table_v.dtype)
            row = jnp.dot(oh, table_v, preferred_element_type=jnp.float32)
            s, elem, done, contrib = _advance_cols(
                row, s, elem, dest_c, d0_c, effw_c, done, tol, one_k
            )
            fl = fl + jnp.dot(contrib[None, :], oh,
                              preferred_element_type=jnp.float32)
            return it + jnp.int32(1), s, elem, done, fl

        def cond(carry):
            it, _, _, done, _ = carry
            return (it < max_iters) & jnp.any(~done)

        it0 = jnp.int32(0)
        _, s, elem, done, fl = lax.while_loop(
            cond, body,
            (it0, s_ref[:], elem_ref[:], done_ref[:] != 0,
             jnp.zeros((1, L), jnp.float32)),
        )
        s_out[:] = s
        elem_out[:] = elem
        done_out[:] = done.astype(jnp.int8)
        flux_out[:] = fl

    tile = lambda: pl.BlockSpec((W_TILE,), lambda t: (t,))  # noqa: E731
    tile3 = lambda: pl.BlockSpec((W_TILE, 3), lambda t: (t, 0))  # noqa: E731
    full = pl.BlockSpec((L, 32), lambda t: (0, 0))
    s_o, elem_o, done_o, fparts = pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[full, tile(), tile(), tile(), tile3(), tile3(), tile()],
        out_specs=[tile(), tile(), tile(),
                   pl.BlockSpec((1, L), lambda t: (t, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((T * W_TILE,), fdtype),
            jax.ShapeDtypeStruct((T * W_TILE,), jnp.int32),
            jax.ShapeDtypeStruct((T * W_TILE,), jnp.int8),
            jax.ShapeDtypeStruct((T, L), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, L), jnp.float32)],
        interpret=interpret,
    )(table, jnp.zeros_like(seg), elem, done0.astype(jnp.int8),
      dest, d0, eff_w)
    s_o, elem_o = s_o[:n], elem_o[:n]
    done = done_o[:n] != 0
    dest, d0 = dest[:n], d0[:n]
    flux = flux + jnp.sum(fparts, axis=0).astype(flux.dtype)
    exited = done & (s_o < one)
    x_fin = jnp.where((done & ~exited)[:, None], dest,
                      dest + (s_o - one)[:, None] * d0)
    return x_fin, elem_o, done, exited, flux


# ---------------------------------------------------------------------------

def _setup(divs, n, seed=0):
    mesh = build_box(1, 1, 1, divs, divs, divs, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    src = rng.uniform(0.05, 0.95, (n, 3)).astype(np.float32)
    dest = np.clip(
        src + rng.normal(scale=0.25 / np.sqrt(3), size=(n, 3)), 0.02, 0.98
    ).astype(np.float32)
    from pumiumtally_tpu.api.tally import _localize_step

    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    x, elem, done, _ = _localize_step(
        mesh, jnp.broadcast_to(c0, (n, 3)), jnp.zeros((n,), jnp.int32),
        jnp.asarray(src), tol=1e-6, max_iters=4096,
    )
    assert bool(jnp.all(done))
    return mesh, x, elem, jnp.asarray(dest)


def check():
    n = 2000
    for divs in (3, 5):
        mesh, x, elem, dest = _setup(divs, n)
        fly = jnp.ones((n,), jnp.int8)
        w = jnp.asarray(np.random.default_rng(1).uniform(0.5, 1.5, n),
                        jnp.float32)
        f0 = jnp.zeros((mesh.nelems,), jnp.float32)
        ref = walk(mesh, x, elem, dest, fly, w, f0, tally=True, tol=1e-6,
                   max_iters=4096)
        for name, fn in (
            ("onehot_jnp", walk_onehot_jnp),
            ("pallas_interpret",
             partial(walk_vmem_pallas, interpret=True)),
        ):
            xf, ef, df, exf, fl = fn(mesh, x, elem, dest, fly, w, f0,
                                     tol=1e-6, max_iters=4096)
            assert bool(jnp.all(df)), name
            # The column-wise dot products round differently from the
            # einsum, so a destination ON a tet face may resolve to the
            # face-adjacent neighbor (same benign class as partitioned
            # mode) — bound the fraction instead of requiring equality.
            mism = float(np.mean(np.asarray(ef) != np.asarray(ref.elem)))
            assert mism < 0.01, (name, mism)
            np.testing.assert_allclose(np.asarray(xf), np.asarray(ref.x),
                                       atol=2e-6, err_msg=name)
            np.testing.assert_allclose(
                np.asarray(fl), np.asarray(ref.flux), rtol=2e-4, atol=1e-5,
                err_msg=name)
            print(f"divs={divs} {name}: OK "
                  f"(sum flux {float(jnp.sum(fl)):.4f} "
                  f"vs {float(jnp.sum(ref.flux)):.4f})")


def bench(n):
    for divs in (5, 6, 7, 8):  # L = 750, 1296, 2058, 3072
        mesh, x, elem, dest = _setup(divs, n)
        L = mesh.nelems
        fly = jnp.ones((n,), jnp.int8)
        w = jnp.ones((n,), jnp.float32)
        f0 = jnp.zeros((L,), jnp.float32)
        rows = {}
        for name, fn in (
            ("walk_gather", partial(walk, tally=True)),
            ("onehot_jnp", walk_onehot_jnp),
            ("pallas_vmem", walk_vmem_pallas),
        ):
            try:
                g = jax.jit(partial(fn, tol=1e-6, max_iters=4096))
                out = g(mesh, x, elem, dest, fly, w, f0)
                fl = out.flux if hasattr(out, "flux") else out[4]
                float(jnp.sum(fl))  # sync
                t0 = time.perf_counter()
                reps = 3
                for _ in range(reps):
                    out = g(mesh, x, elem, dest, fly, w, f0)
                fl = out.flux if hasattr(out, "flux") else out[4]
                float(jnp.sum(fl))
                dt = (time.perf_counter() - t0) / reps
                rows[name] = n / dt
            except Exception as e:  # noqa: BLE001 — lowering may fail
                rows[name] = f"FAILED: {type(e).__name__}: {str(e)[:200]}"
        print(f"L={L}:")
        for k, v in rows.items():
            print(f"  {k:14s} "
                  f"{v if isinstance(v, str) else f'{v/1e6:.2f}M moves/s'}")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "bench":
        bench(int(sys.argv[2]) if len(sys.argv) > 2 else 500_000)
    else:
        check()
