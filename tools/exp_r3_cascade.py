"""Round-3 cascade A/B on the current backend: stage-boundary
permutation mode ("arrays" r2 form / "packed" / "indirect") ×
window_factor × cond_every at bench scale.

The stage-boundary perm-apply was measured the largest cascade
component on v5e (~51 ms/stage for the 8-array form at 500k,
docs/PERF_NOTES.md); "packed" collapses it to 2 row gathers,
"indirect" trades it for a per-iteration [W,8] ray gather, and
window_factor > 2 halves the number of boundaries outright.

Usage: python tools/exp_r3_cascade.py [N] [DIV] [MOVES]
"""

from __future__ import annotations

import itertools
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.walk import walk

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
DIV = int(sys.argv[2]) if len(sys.argv) > 2 else 20
MOVES = int(sys.argv[3]) if len(sys.argv) > 3 else 4


def main():
    mesh = build_box(1, 1, 1, DIV, DIV, DIV, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    pts = [rng.uniform(0.05, 0.95, (N, 3)).astype(np.float32)]
    for _ in range(MOVES + 1):
        step = rng.normal(scale=0.25 / np.sqrt(3), size=(N, 3))
        pts.append(np.clip(pts[-1] + step, 0.02, 0.98).astype(np.float32))

    from pumiumtally_tpu.api.tally import _localize_step

    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    x0, e0, done, _ = _localize_step(
        mesh, jnp.broadcast_to(c0, (N, 3)), jnp.zeros((N,), jnp.int32),
        jnp.asarray(pts[0]), tol=1e-6, max_iters=8192,
    )
    assert bool(jnp.all(done))
    fly = jnp.ones((N,), jnp.int8)
    w = jnp.ones((N,), jnp.float32)

    results = []
    sweeps = [
        # (perm_mode, window_factor, cond_every)
        ("arrays", 2, 4),    # round-2 configuration (control)
        ("packed", 2, 4),    # new default
        ("indirect", 2, 4),
        ("packed", 4, 4),
        ("packed", 8, 4),
        ("indirect", 4, 4),
        ("packed", 4, 8),
        ("packed", 2, 8),
        ("packed", 2, 16),
    ]
    for mode, wf, ce in sweeps:
        g = jax.jit(partial(
            walk, tally=True, tol=1e-6, max_iters=8192,
            perm_mode=mode, window_factor=wf, cond_every=ce,
        ))
        # warmup move (compile)
        r = g(mesh, x0, e0, jnp.asarray(pts[1]), fly, w,
              jnp.zeros((mesh.nelems,), jnp.float32))
        float(jnp.sum(r.flux))
        x, e = r.x, r.elem
        flux = r.flux
        t0 = time.perf_counter()
        for m in range(2, MOVES + 2):
            r = g(mesh, x, e, jnp.asarray(pts[m]), fly, w, flux)
            x, e, flux = r.x, r.elem, r.flux
        total = float(jnp.sum(flux))
        dt = time.perf_counter() - t0
        rate = N * MOVES / dt
        results.append((mode, wf, ce, rate, total))
        print(f"perm={mode:8s} wf={wf} cond_every={ce:2d}: "
              f"{rate/1e6:.3f}M moves/s  (sum flux {total:.1f})")

    best = max(results, key=lambda r: r[3])
    print(f"\nbest: perm={best[0]} wf={best[1]} cond_every={best[2]} "
          f"at {best[3]/1e6:.3f}M moves/s")


if __name__ == "__main__":
    main()
