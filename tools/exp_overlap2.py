"""Overlap test with fresh buffers + real compute."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

N = 500_000
rng = np.random.default_rng(0)
fresh = [rng.uniform(size=(N, 3)).astype(np.float32) for _ in range(10)]

@jax.jit
def burn(x):
    def body(i, s):
        return jnp.sin(s) * 1.0001
    return jax.lax.fori_loop(0, 300, body, x)

x0 = jax.device_put(fresh[0]); jax.block_until_ready(x0)
r = burn(x0); jax.block_until_ready(r)

t0 = time.perf_counter(); r = burn(x0); jax.block_until_ready(r)
t_c = time.perf_counter() - t0

t0 = time.perf_counter()
y = jax.device_put(fresh[1]); jax.block_until_ready(y)
t_x1 = time.perf_counter() - t0
t0 = time.perf_counter()
y2 = jax.device_put(fresh[2]); jax.block_until_ready(y2)
t_x2 = time.perf_counter() - t0

t0 = time.perf_counter()
r = burn(x0)
z = jax.device_put(fresh[3])
jax.block_until_ready((r, z))
t_b = time.perf_counter() - t0
print(f"compute={t_c*1e3:.0f}ms xfer_fresh1={t_x1*1e3:.0f}ms xfer_fresh2={t_x2*1e3:.0f}ms "
      f"interleaved={t_b*1e3:.0f}ms sum={1e3*(t_c+t_x1):.0f}ms")

# and: does jnp.asarray(f64, dtype=f32) ship f64?
a64 = rng.uniform(size=(N, 3))
t0 = time.perf_counter(); q = jnp.asarray(a64, dtype=jnp.float32); jax.block_until_ready(q)
print(f"jnp.asarray f64->f32 fresh: {1e3*(time.perf_counter()-t0):.0f}ms")
a64b = rng.uniform(size=(N, 3))
t0 = time.perf_counter(); q2 = jnp.asarray(a64b.astype(np.float32)); jax.block_until_ready(q2)
print(f"pre-cast f32 then asarray fresh: {1e3*(time.perf_counter()-t0):.0f}ms")
