"""API-level protocol A/B on the current backend: two_phase (staging
dedup on), two_phase_forced (everything staged, no pipelining benefit
denied though — unfenced), and continue rates at bench scale.

Quick version of bench.py's workload matrix (fewer moves, no CPU
baseline) for iterating on the staging/pipeline design on-chip.

Usage: python tools/exp_r2_api.py [N] [DIV] [MOVES]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
DIV = int(sys.argv[2]) if len(sys.argv) > 2 else 20
MOVES = int(sys.argv[3]) if len(sys.argv) > 3 else 6


def run(mode: str) -> float:
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, DIV, DIV, DIV)
    cfg = TallyConfig(
        check_found_all=False,
        auto_continue=(mode != "two_phase_forced"),
        fenced_timing=False,
    )
    t = PumiTally(mesh, N, cfg)
    rng = np.random.default_rng(0)
    pts = [rng.uniform(0.05, 0.95, (N, 3))]
    for _ in range(MOVES + 1):
        step = rng.normal(scale=0.25 / np.sqrt(3), size=(N, 3))
        pts.append(np.clip(pts[-1] + step, 0.02, 0.98))
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    def drive(m: int) -> None:
        dests = pts[m].reshape(-1).copy()
        if mode.startswith("two_phase"):
            t.MoveToNextLocation(
                pts[m - 1].reshape(-1).copy(), dests,
                np.ones(N, np.int8), np.ones(N),
            )
        else:
            t.MoveToNextLocation(None, dests)

    drive(1)
    float(jnp.sum(t.flux))  # sync after warmup/compile
    t0 = time.perf_counter()
    for m in range(2, MOVES + 2):
        drive(m)
    total = float(jnp.sum(t.flux))
    dt = time.perf_counter() - t0
    rate = N * MOVES / dt
    hits = getattr(t, "auto_continue_hits", 0)
    print(f"{mode:17s}: {rate:,.0f} moves/s  (sum={total:.1f}, "
          f"echo hits={hits})", flush=True)
    return rate


def main():
    for mode in ("continue", "two_phase", "two_phase_forced"):
        run(mode)


if __name__ == "__main__":
    main()
