"""Round-2 perf experiments on the real chip.

1. Component timings at bench scale (500k particles, 48k tets):
   gather with random vs SORTED indices (is locality worth a sort key?),
   scatter-add random vs sorted, argsort cost.
2. Continue-move breakdown: full cascade vs compact=False.

Run: python tools/exp_r2_profile.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import build_box

N = 500_000
DIV = 20  # 48k tets


def timeit(fn, *args, reps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    # real sync on lazy backends: fetch a scalar
    _ = float(jnp.sum(out[0] if isinstance(out, tuple) else out.ravel()[:1][0]))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _ = float(jnp.sum(out[0] if isinstance(out, tuple) else out.ravel()[:1][0]))
    return (time.perf_counter() - t0) / reps


def main():
    mesh = build_box(1, 1, 1, DIV, DIV, DIV)
    E = mesh.nelems
    table = mesh.walk_table
    rng = np.random.default_rng(0)
    idx_rand = jnp.asarray(rng.integers(0, E, N), jnp.int32)
    idx_sorted = jnp.sort(idx_rand)
    vals = jnp.asarray(rng.uniform(size=N), jnp.float32)

    gather = jax.jit(lambda t, i: t[i])
    t_rand = timeit(gather, table, idx_rand)
    t_sort = timeit(gather, table, idx_sorted)
    print(f"gather[{N}x20] random: {t_rand*1e3:.2f} ms  "
          f"sorted: {t_sort*1e3:.2f} ms  ({t_rand/t_sort:.2f}x)")

    scat = jax.jit(
        lambda i, v: jnp.zeros((E,), jnp.float32).at[i].add(v, mode="drop")
    )
    s_rand = timeit(scat, idx_rand, vals)
    s_sort = timeit(scat, idx_sorted, vals)
    print(f"scatter[{N}->{E}] random: {s_rand*1e3:.2f} ms  "
          f"sorted: {s_sort*1e3:.2f} ms  ({s_rand/s_sort:.2f}x)")

    srt = jax.jit(lambda k: jnp.argsort(k, stable=True))
    t_as = timeit(srt, idx_rand)
    print(f"argsort[{N}] int32: {t_as*1e3:.2f} ms")

    srt2 = jax.jit(lambda k: jnp.argsort(k, stable=True))
    done = jnp.asarray(rng.uniform(size=N) < 0.5)
    t_as2 = timeit(srt2, done)
    print(f"argsort[{N}] bool: {t_as2*1e3:.2f} ms")

    # permutation apply cost (8 arrays as in the cascade)
    def apply_perm(p, x, e, d, f, w, dn, ex, i2):
        return tuple(a[p] for a in (x, e, d, f, w, dn, ex, i2))
    x = jnp.asarray(rng.uniform(size=(N, 3)), jnp.float32)
    arrs = (x, idx_rand, x, vals.astype(jnp.int8), vals, done, done, idx_rand)
    ap = jax.jit(apply_perm)
    perm = jnp.argsort(done, stable=True)
    t_ap = timeit(ap, perm, *arrs)
    print(f"apply perm to 8 arrays: {t_ap*1e3:.2f} ms")

    # cond reduction cost
    red = jax.jit(lambda d: jnp.sum(~d))
    t_red = timeit(red, done)
    print(f"sum(~done)[{N}]: {t_red*1e3:.3f} ms")

    # einsum cost (the 2-projection batched matmul)
    fn_ = jnp.asarray(rng.uniform(size=(N, 4, 3)), jnp.float32)
    dx = jnp.asarray(rng.uniform(size=(N, 3, 2)), jnp.float32)
    ein = jax.jit(lambda a, b: jnp.einsum("nfc,nck->nfk", a, b))
    t_ein = timeit(ein, fn_, dx)
    print(f"einsum [N,4,3]x[N,3,2]: {t_ein*1e3:.2f} ms")


if __name__ == "__main__":
    main()
