#!/bin/bash
# Round-5 tunnel probe loop: probe every ~10 min; when the device
# answers, fire the on-chip suite once and exit. Probing is done in a
# killable child so a wedged tunnel costs one timeout, not a hang.
set -u
OUT=/root/repo/tools/r5_onchip
mkdir -p "$OUT"
LOCK=${PUMIUMTALLY_CHIP_LOCK:-/tmp/pumiumtally_chip.lock}
N=0
while true; do
  N=$((N + 1))
  # Single-client interlock (utils/chiplock.py): ONE lock acquisition
  # (bounded wait — never block for another holder's whole window)
  # covering probe AND suite, so the window cannot be stolen between
  # them. rc: 0 = suite ran, 3 = probe failed, 4 = lock busy (-E 4),
  # anything else = broken probe command (logged distinctly).
  flock -w 30 -E 4 "$LOCK" bash -c '
    if timeout 150 python -c "import jax, jax.numpy as jnp; print(float(jnp.sum(jnp.ones(8))))" >>'"$OUT"'/probe.log 2>&1; then
      echo "probe OK $(date) — firing suite" >> '"$OUT"'/probe.log
      PUMIUMTALLY_CHIP_LOCK_HELD=1 bash /root/repo/tools/r5_onchip_suite.sh
      exit 0
    fi
    exit 3'
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "suite complete $(date)" >> "$OUT/probe.log"
    exit 0
  elif [ "$rc" -eq 3 ]; then
    echo "probe $N failed $(date)" >> "$OUT/probe.log"
  elif [ "$rc" -eq 4 ]; then
    echo "probe $N skipped (chip lock busy) $(date)" >> "$OUT/probe.log"
  else
    echo "probe $N BROKEN (rc=$rc — probe command itself failed) $(date)" >> "$OUT/probe.log"
  fi
  sleep 600
done
