#!/bin/bash
# Round-5 tunnel probe loop: probe every ~10 min; when the device
# answers, fire the on-chip suite once and exit. Probing is done in a
# killable child so a wedged tunnel costs one timeout, not a hang.
set -u
OUT=/root/repo/tools/r5_onchip
mkdir -p "$OUT"
N=0
while true; do
  N=$((N + 1))
  if timeout 150 python -c "import jax, jax.numpy as jnp; print(float(jnp.sum(jnp.ones(8))))" >>"$OUT/probe.log" 2>&1; then
    echo "probe $N OK $(date) — firing suite" >> "$OUT/probe.log"
    bash /root/repo/tools/r5_onchip_suite.sh
    echo "suite complete $(date)" >> "$OUT/probe.log"
    exit 0
  fi
  echo "probe $N failed $(date)" >> "$OUT/probe.log"
  sleep 600
done
