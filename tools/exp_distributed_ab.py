"""Distributed-campaign A/B: collective migration + the 2-process run
(round 13 — bench.py's "distributed" row consumes the JSON line).

Two layers over the IDENTICAL seeded partitioned workload:

- In-process (this interpreter's devices): the global-scatter migrate
  vs ``migrate_collective=True`` (all_gather'd counting-rank keys +
  ppermute ring, parallel/distributed.py). Reported: unfenced rates
  for both arms, FENCED per-move ms (every move synchronized, so the
  spread is attributable), the modeled per-round migration-collective
  bytes (``modeled_migration_collective_bytes`` from the engine's
  actual packed-state layout), and the compiles-healthy contract —
  ``compiles.timed == 0``: the collective path adds ONE phase-program
  variant, compiled in warmup, never in a measured window. Flux
  parity between the arms is asserted BITWISE before any number is
  reported — the determinism contract the pod mode rests on.

- Cross-process (subprocess pair via tests/_distributed_driver.py):
  1 process x 8 virtual CPU devices vs 2 processes x 4, same global
  shapes, global flux/positions/elem_ids compared BITWISE, with each
  worker's fenced campaign wall seconds. On jaxlib builds without
  cross-process CPU collectives (no gloo) this arm reports
  ``{"available": false, "reason": ...}`` honestly instead of failing
  — the in-process parity gate still runs, so the row stays green.
"""

from __future__ import annotations

import json
import os
import re
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _make_batches(rng, n: int, batches: int, moves: int):
    src = rng.uniform(0.1, 0.9, (n, 3))
    segs = [
        np.clip(
            src + rng.normal(scale=0.25, size=(n, 3)), 0.02, 0.98
        )
        for _ in range(moves)
    ]
    return [(src, segs) for _ in range(batches)]


def _drive(t, work):
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())


def _fenced_ms_per_move(t, work, jax):
    """Mean per-move ms with a device fence after every move — the
    attributable cost of one step, no cross-move pipelining."""
    import time

    total = moves = 0.0
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        jax.block_until_ready(t.flux)
        for d in dests:
            t0 = time.perf_counter()
            t.MoveToNextLocation(None, d.reshape(-1).copy())
            jax.block_until_ready(t.flux)
            total += time.perf_counter() - t0
            moves += 1
    return total / moves * 1e3


def _two_process_arm(arms_timeout_ok: bool = True) -> dict:
    """1-proc-x-8 vs 2-proc-x-4 CPU subprocess pair at the same global
    shapes: bitwise npz parity + per-arm fenced campaign seconds."""
    import tempfile

    from tests._distributed_driver import launch_distributed

    def _seconds(outputs):
        for out in outputs:
            m = re.search(r"campaign-seconds=([0-9.]+)", out)
            if m:
                return float(m.group(1))
        return None

    with tempfile.TemporaryDirectory() as td:
        one = os.path.join(td, "one.npz")
        two = os.path.join(td, "two.npz")
        # 2-process arm FIRST: on a jaxlib without gloo it reports
        # unavailable in seconds, before the 1-process arm is paid for.
        res2 = launch_distributed(
            "partitioned", two, num_processes=2, devices_per_proc=4
        )
        if res2.skipped:
            return {"available": False, "reason": res2.reason}
        res1 = launch_distributed(
            "partitioned", one, num_processes=1, devices_per_proc=8
        )
        if res1.skipped:  # pragma: no cover — 1-proc never skips
            return {"available": False, "reason": res1.reason}
        for res in (res1, res2):
            for pid, rc in enumerate(res.returncodes):
                if rc != 0:
                    raise RuntimeError(
                        f"distributed worker {pid} rc={rc}:\n"
                        + res.outputs[pid][-2000:]
                    )
        a, b = np.load(one), np.load(two)
        for k in sorted(a.files):
            if not (a[k] == b[k]).all():
                raise RuntimeError(
                    f"2-process global {k} diverged bitwise from the "
                    "1-process run at the same global shapes"
                )
        return {
            "available": True,
            "parity_bitwise": True,
            "processes": 2,
            "global_devices": 8,
            "one_proc_campaign_s": _seconds(res1.outputs),
            "two_proc_campaign_s": _seconds(res2.outputs),
        }


def run_ab(
    n: int = 50_000,
    div: int = 12,
    moves: int = 2,
    batches: int = 6,
    two_process: bool = True,
) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import (
        PartitionedPumiTally,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.parallel import make_device_mesh
    from pumiumtally_tpu.parallel.distributed import (
        modeled_migration_collective_bytes,
        state_pack_columns,
    )
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    dm = make_device_mesh()
    ndev = int(dm.devices.size)
    rng = np.random.default_rng(23)
    work = _make_batches(rng, n, batches, moves)
    cfg = dict(device_mesh=dm, check_found_all=False,
               capacity_factor=8.0)

    t_scatter = PartitionedPumiTally(mesh, n, TallyConfig(**cfg))
    _drive(t_scatter, work[:2])  # warmup: compiles happen here
    jax.block_until_ready(t_scatter.flux)
    t0 = time.perf_counter()
    _drive(t_scatter, work[2:])
    jax.block_until_ready(t_scatter.flux)
    scatter_s = time.perf_counter() - t0

    with retrace_guard(raise_on_exceed=False) as guard:
        t_coll = PartitionedPumiTally(
            mesh, n, TallyConfig(migrate_collective=True, **cfg)
        )
        _drive(t_coll, work[:2])
        jax.block_until_ready(t_coll.flux)
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            t0 = time.perf_counter()
            _drive(t_coll, work[2:])
            jax.block_until_ready(t_coll.flux)
            coll_s = time.perf_counter() - t0

    # Parity gate: the ppermute-ring migrate must be BITWISE the
    # global scatter, or the pod mode's determinism contract is gone.
    if not bool(jnp.all(t_scatter.flux == t_coll.flux)):
        raise RuntimeError(
            "collective-migrate flux diverged bitwise from the "
            "global-scatter engine"
        )

    st = t_coll.engine.state
    fcols, icols = state_pack_columns(st)
    cap = int(st["pending"].shape[0])
    moves_total = n * moves * (batches - 2)
    two_proc = (
        _two_process_arm() if two_process
        else {"available": False, "reason": "disabled by caller"}
    )
    return {
        "row": "distributed",
        "scatter_moves_per_sec": moves_total / scatter_s,
        "collective_moves_per_sec": moves_total / coll_s,
        "collective_overhead_pct":
            (coll_s - scatter_s) / scatter_s * 100.0,
        "fenced_scatter_ms_per_move":
            _fenced_ms_per_move(t_scatter, work[:2], jax),
        "fenced_collective_ms_per_move":
            _fenced_ms_per_move(t_coll, work[:2], jax),
        "flux_parity_bitwise": True,
        "migration": {
            "modeled_collective_bytes_per_round":
                modeled_migration_collective_bytes(
                    cap, ndev, fcols, icols
                ),
            "float_cols": fcols,
            "int_cols": icols,
            "capacity": cap,
            "devices": ndev,
        },
        "two_process": two_proc,
        # The collective path adds one phase-program variant; it
        # compiles in warmup — never inside the measured window.
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 50_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 12))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 6))
    print(json.dumps(run_ab(n=n, div=div, moves=moves, batches=batches),
                     default=float))


if __name__ == "__main__":
    main()
