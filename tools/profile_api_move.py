"""Time each section of MoveToNextLocation."""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.api.tally import _move_step

N, DIV, MEAN_STEP = 500_000, 20, 0.25
mesh = build_box(1, 1, 1, DIV, DIV, DIV)
t = PumiTally(mesh, N, TallyConfig(check_found_all=False))
rng = np.random.default_rng(0)
pos = rng.uniform(0.05, 0.95, (N, 3))
t.CopyInitialPosition(pos.reshape(-1).copy())
d0 = np.clip(pos + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)
t.MoveToNextLocation(pos.reshape(-1).copy(), d0.reshape(-1).copy(),
                     np.ones(N, np.int8), np.ones(N))
pos = t.positions.astype(np.float64)

for trial in range(3):
    d = np.clip(pos + rng.normal(scale=MEAN_STEP/np.sqrt(3), size=(N,3)), 0, 1)
    po, pd = pos.reshape(-1).copy(), d.reshape(-1).copy()
    fly, w = np.ones(N, np.int8), np.ones(N)
    t0 = time.perf_counter()
    origins = t._as_positions(po, None); dests = t._as_positions(pd, None)
    flyj = jnp.asarray(np.array(fly, dtype=np.int8, copy=True))
    wj = jnp.asarray(w.copy(), dtype=t.dtype)
    jax.block_until_ready((origins, dests, flyj, wj))
    t1 = time.perf_counter()
    x, elem, flux, ok = _move_step(t.mesh, t.x, t.elem, origins, dests,
                                   flyj, wj, t.flux,
                                   tol=t._tol, max_iters=t._max_iters)
    t2 = time.perf_counter()  # dispatch returned (async)
    jax.block_until_ready(flux)
    t3 = time.perf_counter()
    t.x, t.elem, t.flux = x, elem, flux
    pos = np.asarray(t.x, np.float64)
    t4 = time.perf_counter()
    print(f"stage: {1e3*(t1-t0):6.1f} | dispatch: {1e3*(t2-t1):6.1f} | "
          f"device: {1e3*(t3-t2):6.1f} | readback: {1e3*(t4-t3):6.1f} ms")
