"""Autosave overhead A/B + per-save cost capture (r8).

Two arms over the IDENTICAL box workload (same mesh, same seeds, same
per-batch protocol: one CopyInitialPosition + ``moves`` continue-mode
moves per source batch):

- ``off``: the default engine (TallyConfig() — no resilience code
  runs);
- ``on``:  ``checkpoint=CheckpointPolicy(every_n_batches=1)`` — one
  atomic digest-sealed generation written at every batch close
  (keep=2, signal handling off: a bench must not repoint the
  process's SIGINT).

Reported, non-interactively (one JSON line — bench.py's resilience
row consumes it):

- both arms' moves/s and the relative autosave overhead;
- the fenced per-save cost (state fetch + compress + digest + atomic
  rename) and the on-disk generation size;
- generations written/retained (the keep-K prune runs live);
- the compiles-healthy contract (``compiles.timed``; the resilience
  layer is host-side only, so autosave must add ZERO compiles).

Flux parity between the arms is asserted bitwise before any number is
reported — autosave only ever READS engine state, enforced where the
measurement happens.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _make_batches(rng, n: int, batches: int, moves: int):
    src = rng.uniform(0.1, 0.9, (n, 3))
    segs = [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)]
    return [(src, segs) for _ in range(batches)]


def _drive(t, work):
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())


def run_ab(
    n: int = 100_000,
    div: int = 20,
    moves: int = 2,
    batches: int = 8,
) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import (
        CheckpointPolicy,
        PumiTally,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(7)
    work = _make_batches(rng, n, batches, moves)
    ckpt_dir = tempfile.mkdtemp(prefix="pumiumtally_resilience_ab_")
    try:
        t_on = PumiTally(
            mesh, n,
            TallyConfig(
                check_found_all=False, fenced_timing=False,
                checkpoint=CheckpointPolicy(
                    dir=ckpt_dir, every_n_batches=1, keep=2,
                    handle_signals=False,
                ),
            ),
        )
        with retrace_guard(raise_on_exceed=False) as guard:
            _drive(t_on, work[:2])  # warmup: compiles happen here
            jax.block_until_ready(t_on.flux)
            with retrace_guard(raise_on_exceed=False) as timed_guard:
                t0 = time.perf_counter()
                _drive(t_on, work[2:])
                jax.block_until_ready(t_on.flux)
                on_s = time.perf_counter() - t0

        t_off = PumiTally(
            mesh, n, TallyConfig(check_found_all=False, fenced_timing=False)
        )
        _drive(t_off, work[:2])
        jax.block_until_ready(t_off.flux)
        t0 = time.perf_counter()
        _drive(t_off, work[2:])
        jax.block_until_ready(t_off.flux)
        off_s = time.perf_counter() - t0

        # Parity gate: autosave only READS the engine — the on-arm flux
        # must be BITWISE the off-arm flux. RuntimeError (not
        # sys.exit): bench.py wraps this row best-effort.
        if not bool(jnp.all(t_on.flux == t_off.flux)):
            raise RuntimeError(
                "autosave-on flux diverged bitwise from autosave-off"
            )

        # Fenced per-save microcost on the final state (fetch +
        # compress + sha256 + atomic rename), plus the on-disk size.
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            gen, path = t_on.checkpoint_now(bench=True)
        save_ms = (time.perf_counter() - t0) / reps * 1e3
        ckpt_bytes = os.path.getsize(path)
        store = t_on._resilience.store
        gens = store.generations()
        moves_total = n * moves * (batches - 2)
        return {
            "row": "resilience",
            "on_moves_per_sec": moves_total / on_s,
            "off_moves_per_sec": moves_total / off_s,
            "autosave_overhead_pct": (on_s - off_s) / off_s * 100.0,
            "save_ms": save_ms,
            "ckpt_bytes": ckpt_bytes,
            "generations_written": gen,
            "generations_retained": len(gens),
            "keep": t_on.config.checkpoint.keep,
            "flux_parity_bitwise": True,
            # Host-side-only contract: resilience adds no entry points
            # and no compiles anywhere (timed == 0 AND total == the
            # engine's own warmup compiles).
            "compiles": {
                "total": guard.total_compiles,
                "timed": timed_guard.total_compiles,
                **guard.compiles,
            },
            "workload": {
                "particles": n, "mesh_tets": 6 * div**3,
                "moves_per_batch": moves, "batches": batches,
            },
        }
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 8))
    print(json.dumps(run_ab(n=n, div=div, moves=moves, batches=batches),
                     default=float))


if __name__ == "__main__":
    main()
