"""Chipless AOT compile of the FULL multi-chip programs for real TPU
topologies.

The driver's ``dryrun_multichip`` proves the sharded/partitioned
programs compile AND run — but only against virtual CPU devices. This
harness proves the same programs compile for actual multi-chip TPU
targets (v5e 2x2x1 by default): shard_map over a 4-device mesh, psum
collectives, the migration sort/scatter, and (optionally) the Pallas
VMEM walk kernel inside shard_map, all through the real Mosaic+XLA TPU
pipeline via the locally-installed libtpu — no hardware, no tunnel.

Usage: python tools/aot_multichip_compile.py [n_particles]
Prints one OK/FAILED line per program; exit 0 iff all compile.
"""

from __future__ import annotations

import os
import re
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-4")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def tpu_mesh(topology: str = "v5e:2x2x1", axis: str = "dp"):
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology
    )
    return topologies.make_mesh(topo, (len(topo.devices),), (axis,))


def _compile_phase(eng, tmesh) -> float:
    phase = eng._phase_program(tally=True)
    sh = NamedSharding(tmesh, P(tmesh.axis_names[0]))

    def spec(a):
        return None if a is None else jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=sh
        )

    args = (spec(eng.part.table), spec(eng.part.adj_int),
            {k: spec(v) for k, v in eng.state.items()},
            spec(eng.flux_padded))
    t0 = time.perf_counter()
    phase.lower(*args).compile()
    return time.perf_counter() - t0


PROGRAMS = (
    # (topology, label, engine kwargs) — v5e:2x2x1 is the canonical
    # 4-chip certification set; v5e:4x4 extends it across slice size.
    # Chip-generation coverage lives in the scoped-VMEM envelope
    # cross-check after this loop (v5e vs v5p single-chip targets),
    # which established that the scoped limit is a compiler constant —
    # see ops/vmem_walk.py:_chip_vmem_ceiling.
    ("v5e:2x2x1", "partitioned gather phase", {}),
    # Pallas kernel inside shard_map on the multi-TPU target: one
    # VMEM block per chip (3072/4 = 768 <= 1024).
    ("v5e:2x2x1", "partitioned vmem phase", {"vmem_walk_max_elems": 1024}),
    # Sub-split: blocks_per_chip > 1, grid (blocks, tiles).
    ("v5e:2x2x1", "partitioned vmem sub-split phase",
     {"vmem_walk_max_elems": 256}),
    # Gather sub-split (r5 headline bet): lax.map over per-block
    # walk_local inside shard_map — pure XLA, but must be proven
    # against the real TPU pipeline before the bench window.
    ("v5e:2x2x1", "partitioned gather sub-split phase",
     {"vmem_walk_max_elems": 256, "block_kernel": "gather"}),
    ("v5e:4x4", "16-chip gather sub-split phase",
     {"vmem_walk_max_elems": 96, "block_kernel": "gather"}),
)


def main(n: int) -> int:
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.parallel.partition import PartitionedEngine

    mesh = build_box(1, 1, 1, 8, 8, 8, dtype=jnp.float32)  # 3072 tets
    rc = 0
    meshes = {}
    for topology, label, kwargs in PROGRAMS:
        try:
            if topology not in meshes:
                meshes[topology] = tpu_mesh(topology)
            tmesh = meshes[topology]
            eng = PartitionedEngine(
                mesh, tmesh, n, capacity_factor=2.0, tol=1e-6,
                max_iters=256, max_rounds=8, check_found_all=False,
                **kwargs,
            )
            dt = _compile_phase(eng, tmesh)
            blocks = eng.blocks_per_chip
            print(f"OK {label} [{topology}]: {dt:.1f}s "
                  f"(L={eng.part.L}, blocks/chip={blocks}, "
                  f"vmem={eng.use_vmem_walk})")
        except Exception as e:  # noqa: BLE001 — the harness's question
            print(f"FAILED {label} [{topology}]: "
                  f"{type(e).__name__}: {str(e)[:2000]}")
            rc = 1

    # VMEM-envelope cross-check (ADVICE r4 + r5 re-measurement): the
    # scoped-VMEM OOM is PARTICLE-TILE-driven — w_tile=2048 demands
    # ~20.8 MB of Mosaic stack regardless of block length
    # ("exceeded scoped vmem limit", tools/aot_vmem_compile.py
    # 4096 2048 2048 8). Compiling the SAME bare kernel against a v5e
    # AND a v5p single-chip target showed BOTH reject it: the binding
    # limit is the compiler's scoped-stack constant, not physical
    # per-core VMEM (v5p has 2x). This pins the corrected model behind
    # ops/vmem_walk.py:_chip_vmem_ceiling with the real allocator.
    from functools import partial as _partial

    from tools.exp_r4_vmem_compile import chip_workload

    from pumiumtally_tpu.ops.vmem_walk import vmem_walk_local

    _, kargs = chip_workload(divs=8, ndev=2, n=4096)  # L=1536
    f = _partial(vmem_walk_local, tally=True, tol=1e-6, max_iters=2048,
                 w_tile=2048, interpret=False)
    for topology, expect_ok in (("v5p:1x1x1", False), ("v5e:1x1x1", False)):
        label = f"w_tile=2048 vmem kernel on {topology}"
        try:
            # Single-chip topology: a replicated multi-chip sharding
            # would make XLA try to auto-partition the pallas call.
            topo = topologies.get_topology_desc(
                platform="tpu", topology_name=topology,
                chips_per_host_bounds=[1, 1, 1],
            )
            sh = NamedSharding(
                topologies.make_mesh(topo, (1,), ("x",)), P()
            )
            shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
                      for a in kargs]
            t0 = time.perf_counter()
            jax.jit(f).lower(*shaped).compile()
            ok = True
            detail = f"compiled in {time.perf_counter() - t0:.1f}s"
        except Exception as e:  # noqa: BLE001 — outcome under test
            msg = str(e)
            m = re.search(r"size [0-9.]+[MK] .{0,40}limit[^.]*", msg)
            ok = False
            detail = (f"{type(e).__name__}: "
                      f"{m.group(0) if m else msg[:200]}")
        if ok == expect_ok:
            verdict = "compiles" if ok else "correctly rejected"
            print(f"OK {label}: {verdict} ({detail})")
        else:
            print(f"FAILED {label}: expected "
                  f"{'success' if expect_ok else 'rejection'}, got {detail}")
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 4096))
