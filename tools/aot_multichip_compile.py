"""Chipless AOT compile of the FULL multi-chip programs for real TPU
topologies.

The driver's ``dryrun_multichip`` proves the sharded/partitioned
programs compile AND run — but only against virtual CPU devices. This
harness proves the same programs compile for actual multi-chip TPU
targets (v5e 2x2x1 by default): shard_map over a 4-device mesh, psum
collectives, the migration sort/scatter, and (optionally) the Pallas
VMEM walk kernel inside shard_map, all through the real Mosaic+XLA TPU
pipeline via the locally-installed libtpu — no hardware, no tunnel.

Usage: python tools/aot_multichip_compile.py [n_particles]
Prints one OK/FAILED line per program; exit 0 iff all compile.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-4")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def tpu_mesh(n_chips: int = 4, axis: str = "dp"):
    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:2x2x1"
    )
    return topologies.make_mesh(topo, (n_chips,), (axis,))


def _compile_phase(eng, tmesh) -> float:
    phase = eng._phase_program(tally=True)
    sh = NamedSharding(tmesh, P(tmesh.axis_names[0]))

    def spec(a):
        return None if a is None else jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=sh
        )

    args = (spec(eng.part.table), spec(eng.part.adj_int),
            {k: spec(v) for k, v in eng.state.items()},
            spec(eng.flux_padded))
    t0 = time.perf_counter()
    phase.lower(*args).compile()
    return time.perf_counter() - t0


def main(n: int) -> int:
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.parallel.partition import PartitionedEngine

    tmesh = tpu_mesh()
    mesh = build_box(1, 1, 1, 8, 8, 8, dtype=jnp.float32)  # 3072 tets
    rc = 0
    for label, kwargs in (
        ("partitioned gather phase", {}),
        # Pallas kernel inside shard_map on the multi-TPU target: one
        # VMEM block per chip (3072/4 = 768 <= 1024).
        ("partitioned vmem phase", {"vmem_walk_max_elems": 1024}),
        # Sub-split: blocks_per_chip > 1, grid (blocks, tiles).
        ("partitioned vmem sub-split phase",
         {"vmem_walk_max_elems": 256}),
        # Gather sub-split (r5 headline bet): lax.map over per-block
        # walk_local inside shard_map — pure XLA, but must be proven
        # against the real TPU pipeline before the bench window.
        ("partitioned gather sub-split phase",
         {"vmem_walk_max_elems": 256, "block_kernel": "gather"}),
    ):
        try:
            eng = PartitionedEngine(
                mesh, tmesh, n, capacity_factor=2.0, tol=1e-6,
                max_iters=256, max_rounds=8, check_found_all=False,
                **kwargs,
            )
            dt = _compile_phase(eng, tmesh)
            blocks = eng.blocks_per_chip
            print(f"OK {label}: {dt:.1f}s "
                  f"(L={eng.part.L}, blocks/chip={blocks}, "
                  f"vmem={eng.use_vmem_walk})")
        except Exception as e:  # noqa: BLE001 — the harness's question
            print(f"FAILED {label}: {type(e).__name__}: {str(e)[:2000]}")
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 4096))
