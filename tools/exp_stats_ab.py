"""Batch-statistics overhead A/B + trigger-convergence capture (r7).

Two arms over the IDENTICAL box workload (same mesh, same seeds, same
per-batch protocol: one CopyInitialPosition + ``moves`` continue-mode
moves per source batch):

- ``off``: the default engine (TallyConfig() — no stats code runs);
- ``on``:  ``batch_stats=True`` with a ``close_batch()`` at every
  batch boundary.

Reported, non-interactively (one JSON line — the r7 suite's stats_ab
stage and bench.py's batch_stats row both consume it):

- both arms' moves/s and the relative close-batch overhead;
- the fenced per-close cost of the lane update alone and of the full
  close+trigger evaluation (the trigger's single-scalar D2H is the
  sync, so this is an honest wall number);
- the trigger convergence trace on a deterministic alternating-weight
  workload (batch weights 1.0/1.2 -> per-element relative error
  EXACTLY (0.1/1.1)/sqrt(N-1)-shaped): monotone relative-error decay,
  the batch count at which the threshold trigger fired, and the
  1/sqrt(N)-law batches-remaining projection vs what actually
  happened;
- the compiles-healthy contract: jit compiles inside the measured
  window (``compiles.timed``; 0 == every timed batch hit the cache).

Flux parity between the arms is asserted bitwise before any number is
reported — the stats-off-is-identical contract, enforced where the
measurement happens.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _drive_batches(t, pts_by_batch, close_each: bool, trigger=None):
    """Run every (src, dests...) batch through the three-call
    protocol; returns the trigger results of the closes (empty when
    close_each is False)."""
    results = []
    for src, dests in pts_by_batch:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d, w in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy(), None, w)
        if close_each:
            results.append(t.close_batch(trigger))
    return results


def _make_batches(rng, n: int, batches: int, moves: int):
    """Deterministic alternating-weight batches: identical geometry
    every batch, weights 1.0 / 1.2 by batch parity — the per-batch
    flux is w_b * (a fixed pattern), so the expected relative error is
    exactly computable and exactly monotone."""
    src = rng.uniform(0.1, 0.9, (n, 3))
    segs = [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)]
    out = []
    for b in range(batches):
        w = np.full(n, 1.0 if b % 2 == 0 else 1.2)
        out.append((src, [(d, w) for d in segs]))
    return out


def run_ab(
    n: int = 100_000,
    div: int = 20,
    moves: int = 2,
    batches: int = 12,
    threshold: float = 0.04,
) -> dict:
    import time

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, TriggerSpec, build_box
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(7)
    work = _make_batches(rng, n, batches, moves)
    spec = TriggerSpec(threshold=threshold)

    def build(stats: bool) -> PumiTally:
        return PumiTally(
            mesh, n,
            TallyConfig(batch_stats=stats, check_found_all=False,
                        fenced_timing=False),
        )

    # Warmup = the first TWO batches: the close-batch lane update
    # compiles at close #1, but the trigger reduction first runs at
    # close #2 (one closed batch has no variance — evaluation
    # short-circuits on the host), so a one-batch warmup would leak
    # its compile into the timed window.
    t_on = build(True)
    with retrace_guard(raise_on_exceed=False) as guard:
        trig_warm = _drive_batches(t_on, work[:2], close_each=True,
                                   trigger=spec)
        jax.block_until_ready(t_on.flux)
        # -- timed window: stats-ON batches 3..B -------------------------
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            t0 = time.perf_counter()
            trig = _drive_batches(t_on, work[2:], close_each=True,
                                  trigger=spec)
            jax.block_until_ready(
                (t_on.flux, t_on._stats.flux_sum, t_on._stats.flux_sq_sum)
            )
            on_s = time.perf_counter() - t0
    trig = trig_warm + trig

    t_off = build(False)
    _drive_batches(t_off, work[:2], close_each=False)
    jax.block_until_ready(t_off.flux)
    t0 = time.perf_counter()
    _drive_batches(t_off, work[2:], close_each=False)
    jax.block_until_ready(t_off.flux)
    off_s = time.perf_counter() - t0

    # Parity gate: stats-on flux must be BITWISE the stats-off flux —
    # the accumulator only ever reads it. RuntimeError, not
    # sys.exit(): bench.py wraps this row in a best-effort
    # `except Exception`, and a SystemExit would escape it and kill
    # the whole bench (headline included); the CLI main() below still
    # exits nonzero on the unhandled raise.
    if not bool(jnp.all(t_on.flux == t_off.flux)):
        raise RuntimeError(
            "stats-on flux diverged bitwise from stats-off flux"
        )

    # Fenced per-close microcosts on the final accumulated state: the
    # bare lane update (no D2H at all) and the full close+trigger (its
    # scalar fetch is the sync).
    stats = t_on._stats
    from pumiumtally_tpu.stats.accumulators import _close_batch_update
    from pumiumtally_tpu.stats.triggers import evaluate_trigger

    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        s1, s2 = _close_batch_update(
            stats.flux_sum, stats.flux_sq_sum, t_on.flux, stats.open_flux
        )
        jax.block_until_ready((s1, s2))
    lane_ms = (time.perf_counter() - t0) / reps * 1e3
    t0 = time.perf_counter()
    for _ in range(reps):
        evaluate_trigger(stats, spec)  # scalar fetch synchronizes
    trigger_ms = (time.perf_counter() - t0) / reps * 1e3

    # Convergence trace over the timed closes (close #1 happened in
    # the warmup batch): values are inf until 2 batches closed.
    values = [r.value for r in trig]
    finite = [v for v in values if np.isfinite(v)]
    converged_at = next(
        (r.num_batches for r in trig if r.converged), None
    )
    # Projection accuracy: the first finite estimate's implied total
    # vs the actual batch count at convergence.
    first_proj = next(
        (r for r in trig if r.batches_remaining not in (None, 0)), None
    )
    projected_total = (
        None if first_proj is None
        else first_proj.num_batches + first_proj.batches_remaining
    )
    moves_total = n * moves * (batches - 2)
    return {
        "row": "batch_stats",
        "on_moves_per_sec": moves_total / on_s,
        "off_moves_per_sec": moves_total / off_s,
        "close_overhead_pct": (on_s - off_s) / off_s * 100.0,
        "close_lane_update_ms": lane_ms,
        "close_trigger_eval_ms": trigger_ms,
        "flux_parity_bitwise": True,
        "trigger": {
            "metric": spec.metric,
            "threshold": threshold,
            "values": finite,
            "monotone_decay": bool(
                all(b < a for a, b in zip(finite, finite[1:]))
            ),
            "converged_at_batches": converged_at,
            "projected_total_batches": projected_total,
        },
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 100_000))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 20))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 12))
    print(json.dumps(run_ab(n=n, div=div, moves=moves, batches=batches),
                     default=float))


if __name__ == "__main__":
    main()
