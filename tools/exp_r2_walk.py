"""End-to-end walk A/B on the current backend: cond_every sweep +
continue/two-phase rates at bench scale. Run AFTER exp_r2_profile.py
when the chip is available.

Usage: python tools/exp_r2_walk.py [N] [DIV] [MOVES]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.walk import _MIN_WINDOW as _MIN_WINDOW_DEFAULT, walk

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
DIV = int(sys.argv[2]) if len(sys.argv) > 2 else 20
MOVES = int(sys.argv[3]) if len(sys.argv) > 3 else 4


def main():
    import jax

    mesh = build_box(1, 1, 1, DIV, DIV, DIV)
    rng = np.random.default_rng(0)
    pts = [rng.uniform(0.05, 0.95, (N, 3)).astype(np.float32)]
    for _ in range(MOVES):
        step = rng.normal(scale=0.25 / np.sqrt(3), size=(N, 3))
        pts.append(np.clip(pts[-1] + step, 0.02, 0.98).astype(np.float32))

    from functools import partial

    # Localize once; every cond_every variant starts from this state.
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    r0 = jax.jit(partial(walk, tally=False, tol=1e-6, max_iters=4096))(
        mesh, jnp.broadcast_to(c0, (N, 3)), jnp.zeros((N,), jnp.int32),
        jnp.asarray(pts[0]),
        jnp.ones((N,), jnp.int8), jnp.zeros((N,), jnp.float32),
        jnp.zeros((mesh.nelems,), jnp.float32),
    )
    x0, elem0 = r0.x, r0.elem

    def measure(label, **kw):
        stepper = jax.jit(partial(
            walk, tally=True, tol=1e-6, max_iters=4096, **kw,
        ))
        x, elem = x0, elem0
        flux = jnp.zeros((mesh.nelems,), jnp.float32)
        fly = jnp.ones((N,), jnp.int8)
        w = jnp.ones((N,), jnp.float32)
        # warmup
        r = stepper(mesh, x, elem, jnp.asarray(pts[1]), fly, w, flux)
        float(jnp.sum(r.flux))
        x2, e2, fx = r.x, r.elem, r.flux
        t0 = time.perf_counter()
        for m in range(2, MOVES + 1):
            r = stepper(mesh, x2, e2, jnp.asarray(pts[m]), fly, w, fx)
            x2, e2, fx = r.x, r.elem, r.flux
        total = float(jnp.sum(fx))
        dt = time.perf_counter() - t0
        rate = N * (MOVES - 1) / dt
        print(f"{label}: {rate:,.0f} moves/s  (sum={total:.3f})", flush=True)
        return rate

    # Two samples per cond_every before choosing: single timings through
    # the remote tunnel have large run-to-run variance (PERF_NOTES
    # round 2) and everything below conditions on the winner. Noise is
    # one-sided (stalls only ever LOWER a rate), so the best sample is
    # the estimator.
    best_k, best = 1, 0.0
    for k in (1, 2, 4, 8):
        r = max(measure(f"cond_every={k} (a)", cond_every=k),
                measure(f"cond_every={k} (b)", cond_every=k))
        if r > best:
            best_k, best = k, r
    d = _MIN_WINDOW_DEFAULT
    for mw in (d // 2, d, 2 * d, 4 * d):
        # The d entry repeats the walk default on purpose: its delta vs
        # the cond_every sweep above quantifies run-to-run variance.
        label = f"min_window={mw} (cond_every={best_k})"
        if mw == d:
            label += " [= default; variance repeat]"
        measure(label, cond_every=best_k, min_window=mw)
    measure(f"compact=False (cond_every={best_k})",
            cond_every=best_k, compact=False)


if __name__ == "__main__":
    main()
