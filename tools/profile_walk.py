"""Microbenchmark the walk kernel's building blocks on the current backend.

Usage: python tools/profile_walk.py [N] [DIV]

Times, per walk iteration equivalent: the [E,4,3] face gather, the
einsum, the scatter-add tally, a fused single iteration, and the full
walk — to show where TPU time goes and what a Pallas kernel must beat.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu import build_box
from pumiumtally_tpu.api.tally import _move_step, _localize_step

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
DIV = int(sys.argv[2]) if len(sys.argv) > 2 else 20


def bench(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    mesh = build_box(1.0, 1.0, 1.0, DIV, DIV, DIV)
    E = mesh.nelems
    print(f"backend={jax.default_backend()} N={N} E={E} dtype={mesh.coords.dtype}")
    rng = np.random.default_rng(0)
    elem = jnp.asarray(rng.integers(0, E, N), jnp.int32)
    x = jnp.asarray(rng.uniform(0.05, 0.95, (N, 3)), mesh.coords.dtype)
    d = jnp.asarray(rng.normal(size=(N, 3)) * 0.1, mesh.coords.dtype)
    w = jnp.ones((N,), mesh.coords.dtype)
    flux = jnp.zeros((E,), mesh.coords.dtype)

    t = bench(jax.jit(lambda e: (mesh.face_normals[e], mesh.face_offsets[e], mesh.face_adj[e])), elem)
    print(f"gather fn/fo/adj      : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    if mesh.walk_table is not None:
        packed = mesh.walk_table
        t = bench(jax.jit(lambda e: packed[e]), elem)
        print(f"gather packed [E,20]  : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")
    else:
        print("gather packed [E,20]  : (walk_table unavailable at this E/dtype)")

    fn_ = mesh.face_normals[elem]
    fo_ = mesh.face_offsets[elem]

    def geom(fn, fo, x, d):
        denom = jnp.einsum("nfc,nc->nf", fn, d)
        numer = fo - jnp.einsum("nfc,nc->nf", fn, x)
        crossing = denom > 1e-6
        tt = jnp.where(crossing, numer / jnp.where(crossing, denom, 1.0), jnp.inf)
        tt = jnp.maximum(tt, 0.0)
        return jnp.min(tt, axis=1), jnp.argmin(tt, axis=1)

    t = bench(jax.jit(geom), fn_, fo_, x, d)
    print(f"einsum+exit select    : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    t = bench(jax.jit(lambda f, e, c: f.at[e].add(c, mode="drop")), flux, elem, w)
    print(f"scatter-add flux      : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    # sort-based segment-sum alternative
    def seg(f, e, c):
        order = jnp.argsort(e)
        return f + jax.ops.segment_sum(c[order], e[order], num_segments=E)
    t = bench(jax.jit(seg), flux, elem, w)
    print(f"sort+segment_sum      : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    # full localize walk (no tally)
    dest = jnp.clip(x + d, 0.0, 1.0)
    f = lambda: _localize_step(mesh, x, elem, dest, tol=1e-6, max_iters=4096)
    out = f(); jax.block_until_ready(out)
    t = bench(lambda: f()[0], iters=5, warmup=1)
    print(f"localize walk         : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    # full two-phase move
    g = lambda: _move_step(mesh, x, elem, x, dest, jnp.ones((N,), jnp.int8), w,
                           flux, tol=1e-6, max_iters=4096)
    out = g(); jax.block_until_ready(out)
    t = bench(lambda: g()[0], iters=5, warmup=1)
    print(f"two-phase move        : {t*1e3:8.3f} ms  ({N/t/1e6:8.1f} Mptcl/s)")

    # how many lock-step iterations does the walk actually take?
    from pumiumtally_tpu.ops.walk import walk
    r = walk(mesh, x, elem, dest, jnp.ones((N,), jnp.int8), w, flux,
             tally=True, tol=1e-6, max_iters=4096)
    print(f"walk iterations       : {int(r.iters)}")


if __name__ == "__main__":
    main()
