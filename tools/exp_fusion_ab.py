"""Cross-session fusion A/B: fused vs unfused serving throughput (r12).

The round-11 service executes ONE facade call per session per
dispatch; round 12 coalesces compatible sessions' queued moves into
one padded launch (service/fusion.py). This tool measures what that
buys, non-interactively (one JSON line — bench.py's "service_fusion"
row consumes it):

For N_sessions in {1, 4, 8, 32}: the IDENTICAL per-session campaigns
run through two services —

- ``unfused``: ``TallyService(fuse_sessions=False)`` — the round-11
  one-op-at-a-time serving path;
- ``fused``: ``TallyService()`` (fusion on, the default) — compatible
  heads share one ``walk_fused`` launch.

Protocol: every session's WHOLE campaign pre-queues against a stopped
worker (``autostart=False``, deep queues), then the worker starts and
drains it — the steady heavy-traffic backlog, made DETERMINISTIC (one
worker thread, no client-thread races: each batch wave serves the S
sources one at a time, then every move wave as one full-width fused
group; the unfused arm serves the same ops one at a time). Each arm
runs twice: the first pass holds every compile, the measured second
pass must be cache-hits only.

Reported per N: both throughputs, the fused/unfused speedup, and the
device dispatches per move from the service's own fused-vs-solo
telemetry (``fusion_stats``: a K-way fused group is ONE dispatch
where the unfused arm pays K) — the ~N-fold dispatch amortization the
tentpole exists for.

Gates enforced HERE, before any number is reported:

- **bitwise per-session parity**: every served session's flux (both
  arms) equals the solo run of its campaign on a bare facade, bit for
  bit;
- **compiles.timed == 0**: no compile lands inside any measured pass.

The default per-session batch is a power of two, so equal-sized
sessions pack with ZERO padding rows (fusion.padded_total) — the
serving sweet spot. Override via PUMIUMTALLY_AB_N etc. to probe other
regimes (a non-pow2 n measures the dead-row tax too).

Round 20 adds the STREAMING arm (``facade="stream"`` /
PUMIUMTALLY_AB_FACADE=stream): the identical campaigns on
``StreamingTally`` facades, whose queued moves coalesce CHUNK-WISE —
one ``walk_fused`` launch per chunk index with spans
``(chunk_size,) * K``, so one trace key covers every chunk wave of a
K-way group. Same bitwise gates, same telemetry (dispatches count
scheduler pick_group decisions, not per-chunk XLA launches).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

SESSION_COUNTS = (1, 4, 8, 32)


def _campaign(seed: int, n: int, batches: int, moves: int):
    rng = np.random.default_rng(seed)
    return [
        (rng.uniform(0.1, 0.9, (n, 3)),
         [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)])
        for _ in range(batches)
    ]


def _drive_direct(t, work):
    for src, dests in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests:
            t.MoveToNextLocation(None, d.reshape(-1).copy())


def _build(mesh, n, facade, chunk_size):
    from pumiumtally_tpu import PumiTally, StreamingTally, TallyConfig

    cfg = TallyConfig(check_found_all=False, fenced_timing=False)
    if facade == "stream":
        return StreamingTally(mesh, n, chunk_size=chunk_size, config=cfg)
    return PumiTally(mesh, n, cfg)


def _run_arm(mesh, n, works, fuse, batches, moves, facade="mono",
             chunk_size=None):
    """One serving arm: pre-queue every campaign, start the worker,
    time the drain. Returns (seconds, per-session flux, dispatch
    telemetry)."""
    import time

    from pumiumtally_tpu import TallyService

    depth = batches * (moves + 1) + 2
    with TallyService(fuse_sessions=fuse, autostart=False) as svc:
        handles = {
            sid: svc.open_session(_build(mesh, n, facade, chunk_size),
                                  session_id=sid, max_queue=depth)
            for sid in works
        }
        futs = []
        for b in range(batches):
            for sid, h in handles.items():
                src, _dests = works[sid][b]
                futs.append(h.copy_initial_position(
                    src.reshape(-1).copy()
                ))
            for m in range(moves):
                for sid, h in handles.items():
                    _src, dests = works[sid][b]
                    futs.append(h.move(None,
                                       dests[m].reshape(-1).copy()))
        t0 = time.perf_counter()
        svc.start()
        for f in futs:
            f.result(timeout=600)
        fluxes = {
            sid: np.array(h.flux().result(timeout=600))
            for sid, h in handles.items()
        }
        seconds = time.perf_counter() - t0
        stats = dict(svc.fusion_stats)
    return seconds, fluxes, stats


def run_ab(
    n: int = 8_192,
    div: int = 12,
    moves: int = 2,
    batches: int = 8,
    session_counts=SESSION_COUNTS,
    trials: int = 2,
    facade: str = "mono",
    chunk_size: int | None = None,
) -> dict:
    """facade="stream" runs the round-20 arm: streaming facades whose
    queued chunk launches coalesce chunk-wise (one ``walk_fused``
    launch per chunk index, spans ``(chunk_size,) * K``) instead of
    one whole-slab launch per move wave."""
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.utils.profiling import retrace_guard

    if facade == "stream" and chunk_size is None:
        chunk_size = max(1, n // 2)
    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    per = {}
    timed_compiles = 0
    with retrace_guard(raise_on_exceed=False) as guard:
        for s_count in session_counts:
            works = {
                f"s{i}": _campaign(1000 + 17 * i, n, batches, moves)
                for i in range(s_count)
            }

            def measure(fuse):
                """Warmup pass (holds every compile), then ``trials``
                measured passes against the hot jit cache — min wall
                time (least interference) wins; every measured pass
                must be compile-free."""
                nonlocal timed_compiles
                _run_arm(mesh, n, works, fuse, batches, moves,
                         facade, chunk_size)
                best = None
                for _ in range(max(1, trials)):
                    with retrace_guard(raise_on_exceed=False) as tg:
                        got = _run_arm(mesh, n, works, fuse, batches,
                                       moves, facade, chunk_size)
                    timed_compiles += tg.total_compiles
                    if best is None or got[0] < best[0]:
                        best = got
                return best

            unf_s, unf_flux, unf_stats = measure(False)
            fus_s, fus_flux, fus_stats = measure(True)
            # Bitwise per-session parity gate, BOTH arms, before any
            # number is reported.
            for i in range(s_count):
                sid = f"s{i}"
                solo = _build(mesh, n, facade, chunk_size)
                _drive_direct(solo, works[sid])
                solo_flux = np.asarray(solo.flux)
                if not np.array_equal(unf_flux[sid], solo_flux):
                    raise RuntimeError(
                        f"{s_count} sessions: unfused {sid} flux "
                        "diverged bitwise from the solo run"
                    )
                if not np.array_equal(fus_flux[sid], solo_flux):
                    raise RuntimeError(
                        f"{s_count} sessions: FUSED {sid} flux "
                        "diverged bitwise from the solo run"
                    )
            total_moves = s_count * batches * moves
            unf_disp = unf_stats["solo_moves"] + unf_stats["fused_groups"]
            fus_disp = fus_stats["solo_moves"] + fus_stats["fused_groups"]
            per[str(s_count)] = {
                "unfused_moves_per_sec": total_moves * n / unf_s,
                "fused_moves_per_sec": total_moves * n / fus_s,
                "fused_speedup": unf_s / fus_s,
                "unfused_dispatches_per_move": unf_disp / total_moves,
                "fused_dispatches_per_move": fus_disp / total_moves,
                "fused_move_fraction": (
                    fus_stats["fused_moves"] / total_moves
                ),
            }
    return {
        "row": "service_fusion",
        "facade": facade,
        "per_sessions": per,
        "flux_parity_bitwise": True,
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_compiles,
            **guard.compiles,
        },
        "workload": {
            "particles_per_session": n, "mesh_tets": 6 * div**3,
            "moves_per_batch": moves, "batches": batches,
            "chunk_size": chunk_size,
        },
    }


def main() -> None:
    n = int(os.environ.get("PUMIUMTALLY_AB_N", 8_192))
    div = int(os.environ.get("PUMIUMTALLY_AB_DIV", 12))
    moves = int(os.environ.get("PUMIUMTALLY_AB_MOVES", 2))
    batches = int(os.environ.get("PUMIUMTALLY_AB_BATCHES", 8))
    trials = int(os.environ.get("PUMIUMTALLY_AB_TRIALS", 2))
    facade = os.environ.get("PUMIUMTALLY_AB_FACADE", "mono")
    chunk = os.environ.get("PUMIUMTALLY_AB_CHUNK")
    counts = tuple(
        int(x) for x in os.environ.get(
            "PUMIUMTALLY_AB_SESSIONS", "1,4,8,32"
        ).split(",")
    )
    print(json.dumps(
        run_ab(n=n, div=div, moves=moves, batches=batches,
               session_counts=counts, trials=trials, facade=facade,
               chunk_size=None if chunk is None else int(chunk)),
        default=float,
    ))


if __name__ == "__main__":
    main()
